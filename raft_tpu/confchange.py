"""Joint-consensus membership-change engine (host side).

Conf changes are the reference's rare path and stay on host per SURVEY §7
("keep genuinely rare paths on host"): the committed ConfChange entry is
decoded here, the lane's tracker state is pulled off-device, transformed by a
faithful port of the reference `Changer` semantics (reference:
confchange/confchange.go:51-332), and written back as one row update.

Also provides:
- the V1/V2 conf-change data model + byte encoding (the raftpb analog —
  reference: raftpb/raft.proto:152-214, raftpb/confchange.go:27-155). The
  encoding is this engine's own compact struct packing (payload bytes are
  opaque to the reference algorithm, so wire compatibility is not required);
- `restore()` — replay a ConfState onto an empty config (reference:
  confchange/restore.go:26-155);
- the "v1 l2 r3 u4" text DSL used throughout the reference's tests
  (reference: raftpb/confchange.go:121-155).
"""

from __future__ import annotations

import dataclasses
import enum


class ConfChangeType(enum.IntEnum):
    # reference: raftpb/raft.proto:166-171
    ADD_NODE = 0
    REMOVE_NODE = 1
    UPDATE_NODE = 2
    ADD_LEARNER_NODE = 3


class ConfChangeTransition(enum.IntEnum):
    # reference: raftpb/raft.proto:152-165
    AUTO = 0
    JOINT_IMPLICIT = 1
    JOINT_EXPLICIT = 2


@dataclasses.dataclass(frozen=True)
class ConfChangeSingle:
    # reference: raftpb/raft.proto:187-190
    type: int
    node_id: int


@dataclasses.dataclass
class ConfChange:
    """V1 single-step change (reference: raftpb/raft.proto:173-185)."""

    type: int = int(ConfChangeType.ADD_NODE)
    node_id: int = 0
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return ConfChangeV2(
            changes=[ConfChangeSingle(self.type, self.node_id)],
            context=self.context,
        )


@dataclasses.dataclass
class ConfChangeV2:
    """reference: raftpb/raft.proto:192-214."""

    transition: int = int(ConfChangeTransition.AUTO)
    changes: list = dataclasses.field(default_factory=list)
    context: bytes = b""

    def as_v2(self) -> "ConfChangeV2":
        return self

    def enter_joint(self) -> tuple[bool, bool]:
        """(auto_leave, use_joint). reference: raftpb/confchange.go:82-104."""
        if self.transition != ConfChangeTransition.AUTO or len(self.changes) > 1:
            auto_leave = self.transition in (
                ConfChangeTransition.AUTO,
                ConfChangeTransition.JOINT_IMPLICIT,
            )
            return auto_leave, True
        return False, False

    def leave_joint(self) -> bool:
        """reference: raftpb/confchange.go:106-112."""
        return self.transition == ConfChangeTransition.AUTO and not self.changes


# -- byte encoding: the exact gogoproto wire format ------------------------
#
# Encoding is byte-identical to the reference's generated marshal code
# (reference: raftpb/raft.pb.go:1133-1231) so payload sizes — and therefore
# every size-budget decision — agree with Go. Non-nullable scalar fields are
# always written; bytes fields only when non-empty. ConfChange (v1) fields:
# id=1, type=2, node_id=3, context=4. ConfChangeV2: transition=1,
# changes=2 (repeated ConfChangeSingle{type=1, node_id=2}), context=3.
# A ConfChange entry is distinguished from V2 by the Entry.Type, not the
# payload, so decode() takes a `v1` hint with a structural fallback.


def _varint(x: int) -> bytes:
    out = bytearray()
    while x >= 0x80:
        out.append((x & 0x7F) | 0x80)
        x >>= 7
    out.append(x)
    return bytes(out)


def _read_varint(data: bytes, off: int) -> tuple[int, int]:
    x = shift = 0
    while True:
        b = data[off]
        off += 1
        x |= (b & 0x7F) << shift
        if not b & 0x80:
            return x, off
        shift += 7


def encode(cc: ConfChange | ConfChangeV2) -> bytes:
    if isinstance(cc, ConfChange):
        b = b"\x08" + _varint(0)  # id (unused by the harness)
        b += b"\x10" + _varint(int(cc.type))
        b += b"\x18" + _varint(cc.node_id)
        if cc.context:
            b += b"\x22" + _varint(len(cc.context)) + cc.context
        return b
    b = b"\x08" + _varint(int(cc.transition))
    for ch in cc.changes:
        single = b"\x08" + _varint(int(ch.type)) + b"\x10" + _varint(ch.node_id)
        b += b"\x12" + _varint(len(single)) + single
    if cc.context:
        b += b"\x1a" + _varint(len(cc.context)) + cc.context
    return b


def _decode_single(data: bytes) -> ConfChangeSingle:
    t = nid = 0
    off = 0
    while off < len(data):
        tag, off = _read_varint(data, off)
        if tag == 0x08:
            t, off = _read_varint(data, off)
        elif tag == 0x10:
            nid, off = _read_varint(data, off)
        else:
            raise ValueError(f"bad ConfChangeSingle tag {tag:#x}")
    return ConfChangeSingle(t, nid)


def decode(data: bytes, v1: bool | None = None) -> ConfChange | ConfChangeV2:
    """Callers must pass `v1` (from Entry.Type) — the wire payloads are not
    self-describing."""
    if not data:
        return ConfChangeV2()
    if v1 is None:
        raise ValueError("decode() needs the v1 hint (from the entry type)")
    if v1:
        t = nid = 0
        ctx = b""
        off = 0
        while off < len(data):
            tag, off = _read_varint(data, off)
            if tag == 0x08:
                _, off = _read_varint(data, off)
            elif tag == 0x10:
                t, off = _read_varint(data, off)
            elif tag == 0x18:
                nid, off = _read_varint(data, off)
            elif tag == 0x22:
                n, off = _read_varint(data, off)
                ctx = data[off : off + n]
                off += n
            else:
                raise ValueError(f"bad ConfChange tag {tag:#x}")
        return ConfChange(type=t, node_id=nid, context=ctx)
    tr = 0
    changes = []
    ctx = b""
    off = 0
    while off < len(data):
        tag, off = _read_varint(data, off)
        if tag == 0x08:
            tr, off = _read_varint(data, off)
        elif tag == 0x12:
            n, off = _read_varint(data, off)
            changes.append(_decode_single(data[off : off + n]))
            off += n
        elif tag == 0x1A:
            n, off = _read_varint(data, off)
            ctx = data[off : off + n]
            off += n
        else:
            raise ValueError(f"bad ConfChangeV2 tag {tag:#x}")
    return ConfChangeV2(transition=tr, changes=tuple(changes), context=ctx)


def conf_changes_from_string(s: str) -> list[ConfChangeSingle]:
    """reference: raftpb/confchange.go:121-155 — "v1 l2 r3 u4"."""
    ops = {
        "v": ConfChangeType.ADD_NODE,
        "l": ConfChangeType.ADD_LEARNER_NODE,
        "r": ConfChangeType.REMOVE_NODE,
        "u": ConfChangeType.UPDATE_NODE,
    }
    out = []
    for tok in s.split():
        if tok[0] not in ops:
            raise ValueError(f"unknown conf-change op {tok!r}")
        out.append(ConfChangeSingle(int(ops[tok[0]]), int(tok[1:])))
    return out


# -- tracker-side model ----------------------------------------------------


@dataclasses.dataclass
class TrackerConfig:
    """reference: tracker/tracker.go:27-78."""

    voters_in: set = dataclasses.field(default_factory=set)
    voters_out: set = dataclasses.field(default_factory=set)
    learners: set = dataclasses.field(default_factory=set)
    learners_next: set = dataclasses.field(default_factory=set)
    auto_leave: bool = False

    @property
    def joint(self) -> bool:
        return bool(self.voters_out)

    def clone(self) -> "TrackerConfig":
        return TrackerConfig(
            set(self.voters_in),
            set(self.voters_out),
            set(self.learners),
            set(self.learners_next),
            self.auto_leave,
        )


@dataclasses.dataclass
class Progress:
    """Host mirror of one [lane, slot] progress cell."""

    match: int = 0
    next: int = 1
    state: int = 0
    is_learner: bool = False
    recent_active: bool = False
    msg_app_flow_paused: bool = False
    pending_snapshot: int = 0


@dataclasses.dataclass
class ConfState:
    """reference: raftpb/raft.proto:136-151."""

    voters: tuple = ()
    learners: tuple = ()
    voters_outgoing: tuple = ()
    learners_next: tuple = ()
    auto_leave: bool = False


def equivalent(cs1: ConfState, cs2: ConfState) -> str | None:
    """None when the two ConfStates describe the same configuration after
    sorting each id list; a descriptive message on mismatch (the reference
    returns nil/error, raftpb/confstate.go:25-45). Insensitive to ordering
    and nil-vs-empty; sensitive to AutoLeave."""

    def norm(cs: ConfState):
        return (
            tuple(sorted(cs.voters)),
            tuple(sorted(cs.learners)),
            tuple(sorted(cs.voters_outgoing)),
            tuple(sorted(cs.learners_next)),
            bool(cs.auto_leave),
        )

    a, b = norm(cs1), norm(cs2)
    if a != b:
        return (
            f"ConfStates not equivalent after sorting:\n{a}\n{b}\n"
            f"Inputs were:\n{cs1}\n{cs2}"
        )
    return None


def conf_state(cfg: TrackerConfig) -> ConfState:
    return ConfState(
        voters=tuple(sorted(cfg.voters_in)),
        learners=tuple(sorted(cfg.learners)),
        voters_outgoing=tuple(sorted(cfg.voters_out)),
        learners_next=tuple(sorted(cfg.learners_next)),
        auto_leave=cfg.auto_leave,
    )


class ConfChangeError(Exception):
    pass


class Changer:
    """reference: confchange/confchange.go:39-49."""

    def __init__(self, cfg: TrackerConfig, progress: dict[int, Progress], last_index: int):
        self.cfg = cfg
        self.progress = progress
        self.last_index = last_index

    # -- entry points ------------------------------------------------------

    def enter_joint(
        self, auto_leave: bool, ccs: list[ConfChangeSingle]
    ) -> tuple[TrackerConfig, dict[int, Progress]]:
        """reference: confchange/confchange.go:51-78."""
        cfg, trk = self._check_and_copy()
        if cfg.joint:
            raise ConfChangeError("config is already joint")
        if not cfg.voters_in:
            raise ConfChangeError("can't make a zero-voter config joint")
        cfg.voters_out = set(cfg.voters_in)
        self._apply(cfg, trk, ccs)
        cfg.auto_leave = auto_leave
        return self._check_and_return(cfg, trk)

    def leave_joint(self) -> tuple[TrackerConfig, dict[int, Progress]]:
        """reference: confchange/confchange.go:94-121."""
        cfg, trk = self._check_and_copy()
        if not cfg.joint:
            raise ConfChangeError("can't leave a non-joint config")
        for nid in cfg.learners_next:
            cfg.learners.add(nid)
            trk[nid].is_learner = True
        cfg.learners_next = set()
        for nid in list(cfg.voters_out):
            if nid not in cfg.voters_in and nid not in cfg.learners:
                trk.pop(nid, None)
        cfg.voters_out = set()
        cfg.auto_leave = False
        return self._check_and_return(cfg, trk)

    def simple(
        self, ccs: list[ConfChangeSingle]
    ) -> tuple[TrackerConfig, dict[int, Progress]]:
        """reference: confchange/confchange.go:128-145."""
        cfg, trk = self._check_and_copy()
        if cfg.joint:
            raise ConfChangeError("can't apply simple config change in joint config")
        self._apply(cfg, trk, ccs)
        if len(self.cfg.voters_in ^ cfg.voters_in) > 1:
            raise ConfChangeError(
                "more than one voter changed without entering joint config"
            )
        return self._check_and_return(cfg, trk)

    # -- internals (reference: confchange/confchange.go:150-271) -----------

    def _apply(self, cfg, trk, ccs):
        for cc in ccs:
            if cc.node_id == 0:
                continue  # etcd zeroes NodeID for no-op changes
            if cc.type == ConfChangeType.ADD_NODE:
                self._make_voter(cfg, trk, cc.node_id)
            elif cc.type == ConfChangeType.ADD_LEARNER_NODE:
                self._make_learner(cfg, trk, cc.node_id)
            elif cc.type == ConfChangeType.REMOVE_NODE:
                self._remove(cfg, trk, cc.node_id)
            elif cc.type == ConfChangeType.UPDATE_NODE:
                pass
            else:
                raise ConfChangeError(f"unexpected conf type {cc.type}")
        if not cfg.voters_in:
            raise ConfChangeError("removed all voters")

    def _make_voter(self, cfg, trk, nid):
        pr = trk.get(nid)
        if pr is None:
            self._init_progress(cfg, trk, nid, is_learner=False)
            return
        pr.is_learner = False
        cfg.learners.discard(nid)
        cfg.learners_next.discard(nid)
        cfg.voters_in.add(nid)

    def _make_learner(self, cfg, trk, nid):
        pr = trk.get(nid)
        if pr is None:
            self._init_progress(cfg, trk, nid, is_learner=True)
            return
        if pr.is_learner:
            return
        self._remove(cfg, trk, nid)
        trk[nid] = pr  # ...but save the Progress
        if nid in cfg.voters_out:
            cfg.learners_next.add(nid)
        else:
            pr.is_learner = True
            cfg.learners.add(nid)

    def _remove(self, cfg, trk, nid):
        if nid not in trk:
            return
        cfg.voters_in.discard(nid)
        cfg.learners.discard(nid)
        cfg.learners_next.discard(nid)
        if nid not in cfg.voters_out:
            del trk[nid]

    def _init_progress(self, cfg, trk, nid, is_learner):
        if not is_learner:
            cfg.voters_in.add(nid)
        else:
            cfg.learners.add(nid)
        trk[nid] = Progress(
            match=0,
            next=self.last_index,
            is_learner=is_learner,
            # RecentActive so CheckQuorum doesn't immediately depose us
            # (reference: confchange.go:264-268)
            recent_active=True,
        )

    # -- invariants (reference: confchange/confchange.go:276-332) ----------

    def _check_invariants(self, cfg: TrackerConfig, trk: dict[int, Progress]):
        for nid in cfg.voters_in | cfg.voters_out | cfg.learners | cfg.learners_next:
            if nid not in trk:
                raise ConfChangeError(f"no progress for {nid}")
        for nid in cfg.learners_next:
            if nid not in cfg.voters_out:
                raise ConfChangeError(f"{nid} is in LearnersNext, but not Voters[1]")
            if trk[nid].is_learner:
                raise ConfChangeError(
                    f"{nid} is in LearnersNext, but is already marked as learner"
                )
        for nid in cfg.learners:
            if nid in cfg.voters_out:
                raise ConfChangeError(f"{nid} is in Learners and Voters[1]")
            if nid in cfg.voters_in:
                raise ConfChangeError(f"{nid} is in Learners and Voters[0]")
            if not trk[nid].is_learner:
                raise ConfChangeError(f"{nid} is in Learners, but is not marked as learner")
        if not cfg.joint:
            if cfg.learners_next:
                raise ConfChangeError("cfg.LearnersNext must be nil when not joint")
            if cfg.auto_leave:
                raise ConfChangeError("AutoLeave must be false when not joint")

    def _check_and_copy(self):
        cfg = self.cfg.clone()
        trk = {nid: dataclasses.replace(pr) for nid, pr in self.progress.items()}
        self._check_invariants(cfg, trk)
        return cfg, trk

    def _check_and_return(self, cfg, trk):
        self._check_invariants(cfg, trk)
        return cfg, trk


def restore(
    cs: ConfState, last_index: int
) -> tuple[TrackerConfig, dict[int, Progress]]:
    """Replay a ConfState onto an empty config (reference:
    confchange/restore.go:26-155)."""
    outgoing = [
        ConfChangeSingle(int(ConfChangeType.ADD_NODE), nid)
        for nid in cs.voters_outgoing
    ]
    incoming = (
        [
            ConfChangeSingle(int(ConfChangeType.REMOVE_NODE), nid)
            for nid in cs.voters_outgoing
        ]
        + [ConfChangeSingle(int(ConfChangeType.ADD_NODE), nid) for nid in cs.voters]
        + [
            ConfChangeSingle(int(ConfChangeType.ADD_LEARNER_NODE), nid)
            for nid in list(cs.learners) + list(cs.learners_next)
        ]
    )
    cfg, trk = TrackerConfig(), {}
    if not outgoing:
        for cc in incoming:
            cfg, trk = Changer(cfg, trk, last_index).simple([cc])
    else:
        for cc in outgoing:
            cfg, trk = Changer(cfg, trk, last_index).simple([cc])
        cfg, trk = Changer(cfg, trk, last_index).enter_joint(cs.auto_leave, incoming)
    return cfg, trk
