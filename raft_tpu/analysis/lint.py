"""Repo AST lint: env-knob routing, README cross-check, host hygiene.

Five rules, all pure ``ast`` walks — no jax import, no execution:

- **A — env routing**: every *read* of a ``RAFT_TPU_*`` environment
  variable must go through the typed accessors in ``raft_tpu/config.py``
  (``env_flag``/``env_int``/``env_float``/``env_str``/``env_raw``),
  which own the falsy-token grammar (``"0"``/``""``/``"off"``) and the
  numeric parsing.
  A stray ``os.environ.get("RAFT_TPU_X")`` grows a knob with its own
  private truthiness — the exact drift this rule exists to stop.
  *Writes* stay legal: benches pin planes with
  ``os.environ["RAFT_TPU_X"] = "1"`` / ``setdefault`` / subprocess
  ``dict(os.environ, RAFT_TPU_X=...)`` envs, and none of those reads
  the knob.
- **B — README cross-check**: the set of ``RAFT_TPU_*`` names passed
  as literals to the config accessors anywhere in scope must equal the
  set of rows in README.md's env tables (``| `RAFT_TPU_X` | ... |``).
  A knob the README doesn't list is invisible to operators; a row no
  accessor reads is stale documentation.
- **C — host-plane hygiene**: the host-plane modules (the serving
  router and loop, the WAL/egress/trace stream resolvers, the metrics
  puller, the trace assembler) must not touch device values outside the
  named resolve points: no ``jnp.*`` usage, and no implicit-sync call
  (``np.asarray``/``np.array``/``jax.block_until_ready``/
  ``jax.device_get``/``.item()``/``.tolist()``) outside the allowlist.
  Everything else in those modules must stay plain-numpy/pure-python so
  a dispatch block never gains a hidden device round-trip.
- **C' — bench hygiene**: the same visitor runs over ``benches/*.py``
  with a per-file function allowlist (``BENCH_ALLOW``).  Bench drivers
  *are* supposed to dispatch and block — but only inside the named
  measurement functions, so a stray sync in argument parsing or report
  printing can't silently join the timed region.  A new bench file must
  add its own row.
- **D — donation escape (host view)**: the donation escape proof in
  ``jaxpr_audit.py`` covers the compiled program; this rule covers the
  host side of the same invariant.  In the modules that consume device
  views (``ESCAPE_SCOPE``), a ``self.X = ...`` assignment whose value
  calls a device-view producer (``DEVICE_VIEW_CALLS``) must also pass
  it through a host copy (``HOST_COPY_CALLS``) — otherwise the object
  holds a live reference into a buffer the next donated dispatch will
  invalidate.  Attributes ending ``_pending``/``_inflight`` are exempt:
  that suffix *is* the repo's declared discipline for intentionally
  deferred device handles resolved before the next dispatch.
"""

from __future__ import annotations

import ast
import os
import re

from raft_tpu.analysis.jaxpr_audit import Finding

_KNOB = "RAFT_TPU_"
_ACCESSORS = ("env_flag", "env_int", "env_float", "env_str", "env_raw")

# README env-table rows: | `RAFT_TPU_X` | default | effect |
_README_ROW_RE = re.compile(r"^\|\s*`(RAFT_TPU_[A-Z0-9_]+)`", re.MULTILINE)

# rule C scope: module path (repo-relative) -> allowlisted functions.
# These are the stream/bundle RESOLVE points where a host copy of device
# data is the whole job; bridge.py (state reconstruction) and the device
# planes themselves are out of scope by design.
HOST_PLANE_ALLOW = {
    "raft_tpu/serve/router.py": {"on_bundle"},
    "raft_tpu/serve/loop.py": set(),
    "raft_tpu/runtime/wal.py": {"_resolve"},
    "raft_tpu/runtime/egress.py": {"_resolve_pending", "merge_delta_bundles"},
    "raft_tpu/runtime/trace.py": {"_resolve_pending"},
    "raft_tpu/metrics/host.py": {"_delta", "pull"},
    "raft_tpu/trace/assemble.py": {"merge_block_events", "assemble", "explain"},
}

# rule C' scope: bench file (repo-relative under benches/) -> functions
# allowed to dispatch/sync.  Benches are drivers, so device traffic is
# the point — but it must live in the named measurement functions, not
# leak into argument parsing or report printing.  A bench file absent
# from this table lints with an empty allowlist until a row is added.
BENCH_ALLOW = {
    "benches/__init__.py": set(),
    "benches/baseline_configs.py": {
        "config1_single_group_proposals", "config2_1k_groups_heartbeat",
        "config3_fanin_100k_x5", "config4_joint_consensus_replace_leader",
    },
    "benches/bridge_bench.py": set(),
    "benches/bridge_fused_bench.py": {"_host_b", "main"},
    "benches/chaos_soak.py": set(),
    "benches/confchange_soak.py": set(),
    "benches/diet_ab.py": {"child"},
    "benches/dispatch_ab.py": set(),
    "benches/egress_ab.py": set(),
    "benches/fabric_ab.py": {"child"},
    "benches/latency_probe.py": {"measure", "measure_blocked"},
    "benches/lease_ab.py": {"child"},
    "benches/metrics_smoke.py": set(),
    "benches/multichip_ab.py": set(),
    "benches/paged_ab.py": {"child"},
    "benches/pallas_ab.py": {"child"},
    "benches/pallas_probe.py": {"main"},
    "benches/profile_analyze.py": set(),
    "benches/profile_capture.py": {"main"},
    "benches/roundtime.py": {"main"},
    "benches/scaling_probe.py": {"measure"},
    "benches/serve_bench.py": {"pct"},
    "benches/soak.py": {"main"},
    "benches/tier_ab.py": {"child"},
    "benches/trace_ab.py": {"child"},
    "benches/wal_ab.py": {"fetch_delta", "run"},
}

# rule D scope: host modules that consume device views produced by the
# donated round programs.  Keep in sync with the audit-side escape
# proof in jaxpr_audit.check_donation_escape.
ESCAPE_SCOPE = (
    "raft_tpu/runtime/wal.py",
    "raft_tpu/runtime/egress.py",
    "raft_tpu/runtime/trace.py",
    "raft_tpu/serve/router.py",
    "raft_tpu/serve/loop.py",
)

# Producers whose return values alias (or may alias) donated device
# buffers...
DEVICE_VIEW_CALLS = {
    "host_state", "state_columns", "drain_read_states", "_wal_view",
    "compute_delta", "compute_bundle", "ready_bundle", "delta_bundle",
    "shard_events", "unpack_state", "shard_egress_view", "page_in_view",
}
# ...and the calls that sever the alias by materialising a host copy.
HOST_COPY_CALLS = {
    "asarray", "array", "ascontiguousarray", "device_get", "copy",
    "deepcopy",
}
_ESCAPE_EXEMPT_SUFFIXES = ("_pending", "_inflight")

_SYNC_METHODS = ("item", "tolist")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def scope_files(root: str | None = None) -> list[str]:
    """Rule A/B scope: the package, bench.py, benches/** — not tests/
    (tests legitimately poke raw env to build fixtures)."""
    root = root or repo_root()
    out = []
    for base in ("raft_tpu", "benches"):
        for dirpath, dirnames, filenames in os.walk(os.path.join(root, base)):
            dirnames[:] = [d for d in dirnames if d != "__pycache__"]
            out.extend(
                os.path.join(dirpath, f)
                for f in filenames
                if f.endswith(".py")
            )
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return sorted(out)


def _rel(path: str, root: str) -> str:
    return os.path.relpath(path, root).replace(os.sep, "/")


def _is_os_environ(node) -> bool:
    """node is the expression `os.environ`."""
    return (
        isinstance(node, ast.Attribute)
        and node.attr == "environ"
        and isinstance(node.value, ast.Name)
        and node.value.id == "os"
    )


def _literal_knob(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        if node.value.startswith(_KNOB):
            return node.value
    return None


def check_env_routing(files: list[str], root: str) -> list[Finding]:
    """Rule A. config.py itself is the one legal home for raw reads."""
    out = []
    for path in files:
        rel = _rel(path, root)
        if rel == "raft_tpu/config.py":
            continue
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            knob = None
            # os.environ["RAFT_TPU_X"] in Load context
            if (
                isinstance(node, ast.Subscript)
                and _is_os_environ(node.value)
                and isinstance(node.ctx, ast.Load)
            ):
                knob = _literal_knob(node.slice)
            # os.environ.get("RAFT_TPU_X") / os.getenv("RAFT_TPU_X")
            elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
                f = node.func
                is_environ_get = f.attr == "get" and _is_os_environ(f.value)
                is_os_getenv = (
                    f.attr == "getenv"
                    and isinstance(f.value, ast.Name)
                    and f.value.id == "os"
                )
                if (is_environ_get or is_os_getenv) and node.args:
                    knob = _literal_knob(node.args[0])
            if knob:
                out.append(Finding(rel, "env-routing", (
                    f"line {node.lineno}: raw environment read of {knob} — "
                    "route it through raft_tpu.config (env_flag/env_int/"
                    "env_str/env_raw) so the falsy grammar stays uniform"
                )))
    return out


def collect_knobs(files: list[str]) -> set[str]:
    """Every RAFT_TPU_* literal passed to a config accessor in scope."""
    knobs = set()
    for path in files:
        tree = ast.parse(open(path).read(), filename=path)
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            f = node.func
            name = None
            if isinstance(f, ast.Attribute) and f.attr in _ACCESSORS:
                name = f.attr
            elif isinstance(f, ast.Name) and f.id in _ACCESSORS:
                name = f.id
            if name is None:
                continue
            knob = _literal_knob(node.args[0])
            if knob:
                knobs.add(knob)
    return knobs


def check_readme(files: list[str], root: str) -> list[Finding]:
    """Rule B, both directions."""
    readme = os.path.join(root, "README.md")
    rows = set(_README_ROW_RE.findall(open(readme).read()))
    knobs = collect_knobs(files)
    out = []
    for k in sorted(knobs - rows):
        out.append(Finding("README.md", "readme-table", (
            f"knob {k} is read via config accessors but has no row in "
            "README's env tables — operators can't discover it"
        )))
    for k in sorted(rows - knobs):
        out.append(Finding("README.md", "readme-table", (
            f"README documents {k} but no config accessor reads it — "
            "stale row (or the knob bypasses config.py)"
        )))
    return out


class _HostPlaneVisitor(ast.NodeVisitor):
    def __init__(self, rel, allow):
        self.rel = rel
        self.allow = allow
        self.stack = []
        self.findings = []

    def _allowed(self) -> bool:
        return any(fn in self.allow for fn in self.stack)

    def visit_FunctionDef(self, node):
        self.stack.append(node.name)
        self.generic_visit(node)
        self.stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def _flag(self, node, what):
        self.findings.append(Finding(self.rel, "host-hygiene", (
            f"line {node.lineno}: {what} in host-plane module outside the "
            f"resolve allowlist ({', '.join(sorted(self.allow)) or 'none'})"
            " — host code must stay off the device except at stream "
            "resolve points"
        )))

    def visit_Attribute(self, node):
        if isinstance(node.value, ast.Name):
            base = node.value.id
            if base == "jnp" and not self._allowed():
                self._flag(node, f"jnp.{node.attr} usage")
            elif base == "jax" and node.attr in (
                "block_until_ready", "device_get", "device_put"
            ) and not self._allowed():
                self._flag(node, f"jax.{node.attr} call")
            elif base == "np" and node.attr in ("asarray", "array") \
                    and not self._allowed():
                self._flag(node, f"np.{node.attr} (device sync when fed a"
                           " jax array)")
        self.generic_visit(node)

    def visit_Call(self, node):
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _SYNC_METHODS
            and not isinstance(f.value, ast.Name)  # x.item() on expressions
            and not self._allowed()
        ):
            self._flag(node, f".{f.attr}() call")
        self.generic_visit(node)


def check_host_plane(root: str) -> list[Finding]:
    """Rule C."""
    out = []
    for rel, allow in HOST_PLANE_ALLOW.items():
        path = os.path.join(root, rel)
        if not os.path.exists(path):  # pragma: no cover - layout drift
            out.append(Finding(rel, "host-hygiene",
                               "module listed in HOST_PLANE_ALLOW is gone"))
            continue
        v = _HostPlaneVisitor(rel, allow)
        v.visit(ast.parse(open(path).read(), filename=path))
        out.extend(v.findings)
    return out


def check_bench_hygiene(root: str) -> list[Finding]:
    """Rule C'. Same visitor as rule C, per-file allowlists."""
    out = []
    bench_dir = os.path.join(root, "benches")
    if not os.path.isdir(bench_dir):  # pragma: no cover - layout drift
        return out
    present = {
        "benches/" + f
        for f in os.listdir(bench_dir)
        if f.endswith(".py")
    }
    for rel in sorted(BENCH_ALLOW.keys() - present):
        out.append(Finding(rel, "bench-hygiene",
                           "file listed in BENCH_ALLOW is gone — drop "
                           "the stale row"))
    for rel in sorted(present):
        allow = BENCH_ALLOW.get(rel, set())
        if rel not in BENCH_ALLOW:
            out.append(Finding(rel, "bench-hygiene", (
                "new bench file has no BENCH_ALLOW row — name the "
                "functions allowed to dispatch/sync so stray device "
                "traffic outside them keeps getting flagged"
            )))
        v = _HostPlaneVisitor(rel, allow)
        v.visit(ast.parse(open(os.path.join(root, rel)).read(),
                          filename=rel))
        out.extend(v.findings)
    return out


class _EscapeVisitor(ast.NodeVisitor):
    """Rule D: self.X = <device view> without a host copy."""

    def __init__(self, rel):
        self.rel = rel
        self.findings = []

    @staticmethod
    def _call_names(expr) -> set[str]:
        names = set()
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if isinstance(f, ast.Attribute):
                names.add(f.attr)
            elif isinstance(f, ast.Name):
                names.add(f.id)
        return names

    def _check(self, node, targets, value):
        attrs = [
            t.attr for t in targets
            if isinstance(t, ast.Attribute)
            and isinstance(t.value, ast.Name)
            and t.value.id == "self"
            and not t.attr.endswith(_ESCAPE_EXEMPT_SUFFIXES)
        ]
        if not attrs or value is None:
            return
        calls = self._call_names(value)
        views = calls & DEVICE_VIEW_CALLS
        if views and not (calls & HOST_COPY_CALLS):
            self.findings.append(Finding(self.rel, "view-escape", (
                f"line {node.lineno}: self.{attrs[0]} stores the result "
                f"of {'/'.join(sorted(views))} without a host copy — the "
                "view aliases a donated device buffer that the next "
                "dispatch invalidates; copy it (np.asarray/…) or use a "
                "*_pending/*_inflight slot resolved before the next "
                "dispatch"
            )))

    def visit_Assign(self, node):
        self._check(node, node.targets, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._check(node, [node.target], node.value)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._check(node, [node.target], node.value)
        self.generic_visit(node)


def check_view_escape(root: str) -> list[Finding]:
    """Rule D."""
    out = []
    for rel in ESCAPE_SCOPE:
        path = os.path.join(root, rel)
        if not os.path.exists(path):  # pragma: no cover - layout drift
            out.append(Finding(rel, "view-escape",
                               "module listed in ESCAPE_SCOPE is gone"))
            continue
        v = _EscapeVisitor(rel)
        v.visit(ast.parse(open(path).read(), filename=path))
        out.extend(v.findings)
    return out


def run_lint(root: str | None = None) -> tuple[list[Finding], dict]:
    """All five rules; returns (findings, report)."""
    root = root or repo_root()
    files = scope_files(root)
    findings = []
    findings += check_env_routing(files, root)
    findings += check_readme(files, root)
    findings += check_host_plane(root)
    findings += check_bench_hygiene(root)
    findings += check_view_escape(root)
    report = {
        "files_scanned": len(files),
        "knobs": sorted(collect_knobs(files)),
        "host_plane_modules": sorted(HOST_PLANE_ALLOW),
        "bench_modules": sorted(BENCH_ALLOW),
        "escape_modules": sorted(ESCAPE_SCOPE),
    }
    return findings, report
