"""Declarative manifest of the repo's compiled entry points.

Every jitted program the engines dispatch — the XLA fused scan, the
pallas K-round megakernel, the quorum kernels, the egress ready/delta
kernels, the diet rebase jits, the paged host-boundary ops, and the
shard_mapped sharded stepper — appears here as one Entry: a builder
that constructs the audit record(s) under a pinned env profile, the
invariants the auditor must hold against it, and the recompile budget
the compile-watch sentinel (analysis/recompile.py) enforces.

The builders construct real clusters/operands but never dispatch a
round: tracing (jax.make_jaxpr) and lowering (.lower()) are the only
jax entry points the auditor touches, and autotune is pinned off in
every profile so the pallas resolvers stay dispatch-free
(FusedCluster._resolve_pallas_tile / _resolve_pallas_rounds fall to
default_tile / K=1 when RAFT_TPU_PALLAS_AUTOTUNE=0).
"""

from __future__ import annotations

import contextlib
import dataclasses
import os

__all__ = [
    "Entry",
    "ENTRIES",
    "env_profile",
    "PROFILES",
    "build_records",
    "entry_names",
]


@contextlib.contextmanager
def env_profile(knobs: dict):
    """Pin RAFT_TPU_* knobs for the duration of a builder; a None value
    unsets the variable. Restores the caller's environment on exit so
    profiles compose with whatever the invoking shell pinned."""
    saved = {k: os.environ.get(k) for k in knobs}
    try:
        for k, v in knobs.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = str(v)
        yield
    finally:
        for k, old in saved.items():
            if old is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = old


# autotune stays off in every profile: the (tile, K) sweep dispatches
# warmup blocks, and the auditor must never execute a round
_BASE = {
    "RAFT_TPU_PALLAS_AUTOTUNE": "0",
    "RAFT_TPU_PALLAS_TILE": None,
    "RAFT_TPU_PALLAS_ROUNDS": None,
    "RAFT_TPU_UNROLL": None,
    "RAFT_TPU_ROUTE": None,
    # the tier plane is pinned OFF in every profile except "tier": the
    # RAFT_TPU_TIER=0 elision claim is asserted on every other entry
    "RAFT_TPU_TIER": None,
    # in-kernel paging is pinned OFF everywhere except its own profile:
    # the host-boundary page_in/page_out records must stay the baseline
    "RAFT_TPU_PAGED_INKERNEL": None,
    # the cross-host fabric is host-side plumbing around the round
    # program (no round-jaxpr footprint), pinned off except in its own
    # profile where its extract/inject boundary jits are audited
    "RAFT_TPU_FABRIC": None,
    # the leader-lease plane is pinned OFF in every profile except
    # "lease": the RAFT_TPU_LEASE=0 elision claim (no lease op, carry
    # bytes/lane unchanged) is asserted on every other entry
    "RAFT_TPU_LEASE": None,
    "RAFT_TPU_LEASE_MARGIN": None,
}

PROFILES = {
    # every optional plane compiled in, packed diet carry, donating twins:
    # the maximal jaxpr — elision (planes present), dtype discipline
    # (packed avals as scan carry), donation, capture, hygiene
    "planes_on": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="1",
        RAFT_TPU_TRACELOG="1",
        RAFT_TPU_DIET="1",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="0",
    ),
    # every plane off, copying twins: the minimal jaxpr — elision (no
    # plane op may survive) and the no-alias check on the copying twin
    "planes_off": dict(
        _BASE,
        RAFT_TPU_METRICS="0",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="0",
        RAFT_TPU_DONATE="0",
        RAFT_TPU_PAGED="0",
    ),
    # the paged entry log on (default geometry), metrics riding along
    "paged": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="0",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="1",
        RAFT_TPU_PAGE_WINDOW=None,
        RAFT_TPU_PAGE_ENTRIES=None,
        RAFT_TPU_POOL_PAGES=None,
    ),
    # diet + paged composed: the packed carry's narrow columns must
    # survive the scan carry AND the resident-window split at once — the
    # maximal byte-savings configuration the capacity claims quote
    "diet_paged": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="1",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="1",
        RAFT_TPU_PAGE_WINDOW=None,
        RAFT_TPU_PAGE_ENTRIES=None,
        RAFT_TPU_POOL_PAGES=None,
    ),
    # the serving frontend's profile: egress is mandatory (commit
    # discovery rides the DeltaBundle sink), diet on, chaos/trace off —
    # the production loop ROADMAP item 3 ships with
    "serve": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="1",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="0",
        RAFT_TPU_EGRESS="1",
    ),
    # in-kernel paged megakernel (RAFT_TPU_PAGED_INKERNEL=1): the paged
    # profile with paging fused into the K-round grid — the tile is
    # pinned to 6 so the 12-lane audit cluster runs TWO grid steps (two
    # allocation segments: pool addressing, not just the 1-tile special
    # case, is what gets audited)
    "paged_inkernel": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="0",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="1",
        RAFT_TPU_PAGED_INKERNEL="1",
        RAFT_TPU_PALLAS_TILE="6",
        RAFT_TPU_PAGE_WINDOW=None,
        RAFT_TPU_PAGE_ENTRIES=None,
        RAFT_TPU_POOL_PAGES=None,
    ),
    # the hot/cold tier's dispatch-boundary jits (tier/engine.py): planes
    # off so the gather/scatter jaxprs are pure row movement, donation on
    # (the scatter's dominant tier-on path consumes the carry in place)
    "tier": dict(
        _BASE,
        RAFT_TPU_METRICS="0",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="0",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="0",
        RAFT_TPU_TIER="1",
    ),
    # the leader-lease plane on (ISSUE 20): the serve profile plus
    # RAFT_TPU_LEASE=1 — the lease columns ride the packed scan carry
    # (uint16 countdown/epoch/skew under diet) and the lease maintenance
    # ops must be IN this jaxpr and in no other entry's
    "lease": dict(
        _BASE,
        RAFT_TPU_METRICS="1",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="1",
        RAFT_TPU_DONATE="1",
        RAFT_TPU_PAGED="0",
        RAFT_TPU_EGRESS="1",
        RAFT_TPU_LEASE="1",
    ),
    # the cross-host fabric's dispatch-boundary jits (fabric/extract.py,
    # fabric/inject.py): planes off so the jaxprs are pure gather/scatter
    # over the fabric carry; the carry buffers they return feed the next
    # donated round, so hygiene/donation are load-bearing
    "fabric": dict(
        _BASE,
        RAFT_TPU_METRICS="0",
        RAFT_TPU_CHAOS="0",
        RAFT_TPU_TRACELOG="0",
        RAFT_TPU_DIET="0",
        RAFT_TPU_DONATE="0",
        RAFT_TPU_PAGED="0",
        RAFT_TPU_FABRIC="1",
    ),
}


@dataclasses.dataclass(frozen=True)
class Entry:
    """One manifest row: name must equal the record's name (the sentinel
    keys its per-entry compile budget by it), profile keys PROFILES,
    build returns the audit record list, expect_on is the plane→bool
    map the elision check asserts, diet gates the dtype-discipline
    check, and compile_budget is the max fresh XLA compilations the
    recompile sentinel tolerates for this entry across the canonical
    smoke (warmup included)."""

    name: str
    profile: str
    build: object
    compile_budget: int = 1
    expect_on: dict | None = None
    diet: bool = False


# -- builders --------------------------------------------------------------
# Small geometry: 4 groups x 3 voters = 12 lanes traces in well under a
# second per entry and exercises every plane. The sharded stepper needs
# the 8-device host platform (runtests.sh / __main__ set XLA_FLAGS).


def _cluster(engine, **kw):
    from raft_tpu.ops.fused import FusedCluster

    return FusedCluster(n_groups=4, n_voters=3, engine=engine, **kw)


def _round_xla():
    return _cluster("xla").audit_programs()


def _round_xla_off():
    recs = _cluster("xla").audit_programs()
    for r in recs:
        r["name"] = r["name"] + ".planes_off"
    return recs


def _round_pallas():
    return _cluster("pallas", rounds_per_call=2).audit_programs()


def _round_xla_lease():
    # check_quorum on: the lease grant predicate requires it (the
    # follower in-lease vote rejection is the safety other half), and the
    # audited jaxpr should be the configuration the plane actually runs in
    recs = _cluster("xla", check_quorum=True).audit_programs()
    for r in recs:
        r["name"] = r["name"] + ".lease"
    return recs


def _round_pallas_inkernel():
    recs = _cluster("pallas", rounds_per_call=2).audit_programs()
    for r in recs:
        r["name"] = "round.pallas.paged_inkernel"
        # hard ledger cap (survives --update-ledger): the whole point of
        # in-kernel paging is that the TWO whole-fleet [N, W] gather/
        # scatter passes and their full-window HBM temporary are gone.
        # One full-window log-column set costs W * 3 cols * 4 B = 192
        # B/lane at the default W=16 split; the program measures ~7262
        # B/lane of temps on the CPU interpret lowering, so a cap of
        # 7400 leaves jitter headroom while any full-window temporary
        # (>= +192) trips it
        r["temp_cap_per_lane"] = 7400.0
    return recs


def _round_diet_paged():
    import jax

    from raft_tpu.ops import paged as pgmod

    cl = _cluster("xla")
    recs = cl.audit_programs()
    # inside the fused round the packed log columns legitimately ride at
    # the paged-in FULL-window shape (page_in at entry, page_out at
    # exit); eval_shape gives those avals without dispatching, and the
    # dtype-discipline check runs against them instead of the resident
    # carry — dtype must still survive, only the window dim widens
    full, _ = jax.eval_shape(pgmod.page_in, cl.state, cl.paged)
    for r in recs:
        r["name"] = r["name"] + ".diet_paged"
        r["dtype_carry"] = [full, r["args"][1]]
    return recs


def _sharded_step():
    import jax

    from raft_tpu.parallel.sharded import ShardedFusedCluster

    if len(jax.devices()) < 2:  # pragma: no cover - single-device hosts
        return []
    return ShardedFusedCluster(n_groups=16, n_voters=3).audit_programs()


def _mesh_step():
    import jax

    from raft_tpu.parallel.mesh import MeshBlockedCluster

    if len(jax.devices()) < 2:  # pragma: no cover - single-device hosts
        return []
    # two blocks of 16 groups over the 8-device host mesh — the smallest
    # geometry where the mesh driver is more than one sharded cluster
    return MeshBlockedCluster(
        n_groups=32, n_voters=3, block_groups=16
    ).audit_programs()


def _serve_round():
    from raft_tpu.serve.loop import ServeLoop

    return ServeLoop(_cluster("xla")).audit_programs()


def _quorum_operands():
    import numpy as np

    import jax.numpy as jnp

    rng = np.random.default_rng(7)
    n, v = 256, 3
    match = jnp.asarray(rng.integers(0, 1 << 20, (n, v)), jnp.int32)
    m_in = jnp.asarray(rng.random((n, v)) < 0.8)
    m_out = jnp.asarray(rng.random((n, v)) < 0.4)
    return match, m_in, m_out


def _quorum_pallas():
    from raft_tpu.ops import quorum_pallas as qp

    match, m_in, m_out = _quorum_operands()
    return [dict(
        name="quorum.pallas",
        fn=qp.joint_committed_pallas,
        jit=qp.joint_committed_pallas,
        args=(match, m_in, m_out),
        kwargs={},
        static=dict(interpret=True),
        donate=False,
        donate_argnums=(),
        donate_argnames=(),
        # operands are plain i32/bool batch tensors, no packed carry;
        # the pallas-specific invariants (constant capture, hygiene)
        # still apply
        checks=("capture", "hygiene", "donation"),
        lanes=match.shape[0],
        rounds=1,
    )]


def _quorum_xla():
    import jax

    from raft_tpu.ops import quorum as qr

    match, m_in, m_out = _quorum_operands()
    return [dict(
        name="quorum.xla",
        fn=qr.joint_committed,
        jit=jax.jit(qr.joint_committed),
        args=(match, m_in, m_out),
        kwargs={},
        static={},
        donate=False,
        donate_argnums=(),
        donate_argnames=(),
        checks=("capture", "hygiene", "donation"),
        lanes=match.shape[0],
        rounds=1,
    )]


def _egress_cursors(state):
    import numpy as np

    from raft_tpu.ops import ready_mask as rm

    n = state.term.shape[0]
    z = np.zeros((n,), np.int32)
    f = np.zeros((n,), bool)
    host = rm.HostCursors(
        prev_term=z, prev_vote=z, prev_commit=z, prev_lead=z,
        prev_state=z, host_pending=f, is_async=f, inprog=z,
        snap_inprog=z, applying=z,
    )
    prev = rm.PrevCursors(term=z, lead=z, state=z, committed=z,
                          applied=z, last=z)
    return host, prev


def _egress_entries():
    import jax.numpy as jnp

    from raft_tpu.ops import ready_mask as rm

    cl = _cluster("xla")
    host, prev = _egress_cursors(cl.state)
    host = rm.HostCursors(*(jnp.asarray(a) for a in host))
    prev = rm.PrevCursors(*(jnp.asarray(a) for a in prev))
    common = dict(
        kwargs={}, static={}, donate=False,
        donate_argnums=(), donate_argnames=(),
        checks=("capture", "hygiene", "donation"),
        lanes=cl.state.term.shape[0], rounds=1,
    )
    return [
        dict(common, name="egress.ready_bundle", fn=rm.ready_bundle,
             jit=rm._bundle_jit, args=(cl.state, host)),
        dict(common, name="egress.delta", fn=rm.delta_bundle,
             jit=rm._delta_jit, args=(cl.state, prev)),
    ]


def _rebase_entries():
    import numpy as np

    import jax.numpy as jnp

    from raft_tpu.ops import fused as fmod
    from raft_tpu.state import unpack_state
    from raft_tpu.ops.fused import unpack_fabric, fat_fabric

    cl = _cluster("xla")
    st = unpack_state(cl.state)
    fb = fat_fabric(unpack_fabric(cl.fab))
    n = st.term.shape[0]
    mask = jnp.asarray(np.ones((n,), bool))
    delta = jnp.asarray(np.zeros((n,), np.int32))
    common = dict(
        kwargs={}, static={},
        # the rebase jits are the PR 2 donate-then-read bug class's
        # original home: carry stability proves the rebased columns
        # come back with their exact avals, escape names any donated
        # leaf that loses its in-place alias
        checks=("capture", "hygiene", "donation", "carry", "escape"),
        lanes=n, rounds=1,
        carry_argnums=(0,), carry_argnames=(),
    )
    return [
        dict(common, name="rebase.indexes", fn=fmod._rebase_indexes,
             jit=fmod._rebase_indexes_donate_jit,
             args=(st, mask, delta), donate=True,
             donate_argnums=(0,), donate_argnames=()),
        dict(common, name="rebase.fabric", fn=fmod._rebase_fabric,
             jit=fmod._rebase_fabric_donate_jit,
             args=(fb, delta), donate=True,
             donate_argnums=(0,), donate_argnames=()),
    ]


def _paged_entries():
    from raft_tpu.ops import paged as pgmod

    cl = _cluster("xla")
    assert cl.paged is not None, "paged profile must enable RAFT_TPU_PAGED"
    # page_out takes the FULL-window carry; recovering it from cl.state
    # via page_in_host would dispatch a program, which the auditor never
    # does — build a twin cluster with paging off for the full carry and
    # pair it with a fresh all-resident sidecar instead
    with env_profile({"RAFT_TPU_PAGED": "0"}):
        full = _cluster("xla")
    paged0 = pgmod.init_paged(cl._page_plan, full.state)
    return pgmod.audit_records(cl.state, cl.paged, full.state, paged0)


def _tier_entries():
    import numpy as np

    import jax
    import jax.numpy as jnp

    from raft_tpu.ops.fused import unpack_fabric
    from raft_tpu.state import unpack_state
    from raft_tpu.tier import engine as tmod

    cl = _cluster("xla")
    assert cl.tier is not None, "tier profile must enable RAFT_TPU_TIER"
    # the gather/scatter operate on the unpacked slim-canonical carry —
    # exactly what TierEngine._commit hands them between page_in/unpack
    # and slim/pack/page_out
    st = unpack_state(cl.state)
    fb = unpack_fabric(cl.fab)
    # one evicted group's voter lanes, duplicate-padded to the next power
    # of two exactly as _commit pads its batches (3 lanes -> 4)
    lanes_np, _ = tmod._pad_rows(np.arange(cl.v, dtype=np.int32), None)
    lanes = jnp.asarray(lanes_np)
    rows = lambda t: jax.tree.map(
        lambda x: jnp.asarray(np.asarray(x)[lanes_np]), t
    )
    st_rows, fb_rows = rows(st), rows(fb)
    gather_jit, _, scatter_donate_jit = tmod._jits()
    n = np.asarray(st.term).shape[0]
    common = dict(kwargs={}, static={}, lanes=n, rounds=1)
    return [
        # evict-snapshot: fresh row buffers off the carry (no donation —
        # the carry must stay valid for the scatter in the same apply)
        dict(common, name="tier.gather", fn=tmod._tier_gather,
             jit=gather_jit, args=(st, fb, lanes), donate=False,
             donate_argnums=(), donate_argnames=(),
             checks=("elision", "capture", "hygiene", "donation")),
        # admit-restore: the donating twin _commit dispatches under
        # RAFT_TPU_DONATE=1 — the carry is the fixpoint (state AND fabric
        # come back with identical avals) and every donated leaf must
        # keep its in-place alias
        dict(common, name="tier.scatter", fn=tmod._tier_scatter,
             jit=scatter_donate_jit,
             args=(st, fb, lanes, st_rows, fb_rows), donate=True,
             donate_argnums=(0, 1), donate_argnames=(),
             checks=("elision", "capture", "hygiene", "donation",
                     "carry", "escape"),
             carry_argnums=(0, 1), carry_argnames=()),
    ]


def _fabric_entries():
    import jax.numpy as jnp

    from raft_tpu.fabric import extract as fx
    from raft_tpu.fabric import inject as fi
    from raft_tpu.fabric.placement import Placement

    # the canonical milestone-1 geometry: two hosts, group 1 spanning
    pl = Placement.mostly_local(4, 3, 2, spanning=(1,))
    cl = _cluster("xla")
    host = 0
    cap = len(fx.CHANNELS) * pl.n_cross_cells(host)
    xedge = jnp.asarray(pl.xedge(host))
    own = jnp.asarray(pl.own_mask(host))
    e = int(cl.fab.rep.ent_term.shape[-1])
    n = cl.state.term.shape[0]
    chan = jnp.zeros((cap,), jnp.int32)
    cell = jnp.zeros((cap,), jnp.int32)
    valid = jnp.zeros((cap,), bool)
    cols = {f: jnp.zeros((cap,), jnp.int32) for f in fx.SCALAR_FIELDS}
    cols.update(
        {f: jnp.zeros((cap, e), jnp.int32) for f in fx.ENT_FIELDS}
    )
    common = dict(
        kwargs={}, static={}, donate=False,
        donate_argnums=(), donate_argnames=(),
        checks=("capture", "hygiene", "donation"),
        lanes=n, rounds=1,
    )
    return [
        dict(common, name="fabric.extract", fn=fx.extract_bundle,
             jit=fx._extract_jit, args=(cl.fab, xedge, own),
             static=dict(cap=cap)),
        dict(common, name="fabric.inject", fn=fi.inject_bundle,
             jit=fi._inject_jit, args=(cl.fab, chan, cell, valid, cols)),
    ]


_ALL_ON = {"metrics": True, "chaos": True, "trace": True, "paged": False,
           "tier": False, "lease": False}
_ALL_OFF = {"metrics": False, "chaos": False, "trace": False,
            "paged": False, "tier": False, "lease": False}
_TIER_ON = {"metrics": False, "chaos": False, "trace": False,
            "paged": False, "tier": True, "lease": False}
_LEASE_ON = {"metrics": True, "chaos": False, "trace": False,
             "paged": False, "tier": False, "lease": True}

ENTRIES = (
    Entry("round.xla", "planes_on", _round_xla,
          compile_budget=1, expect_on=_ALL_ON, diet=True),
    Entry("round.xla.planes_off", "planes_off", _round_xla_off,
          compile_budget=1, expect_on=_ALL_OFF),
    Entry("round.pallas", "planes_on", _round_pallas,
          compile_budget=1, expect_on=_ALL_ON, diet=True),
    Entry("sharded.step.xla", "planes_on", _sharded_step,
          compile_budget=1),
    Entry("quorum.pallas", "planes_on", _quorum_pallas, compile_budget=1),
    Entry("quorum.xla", "planes_on", _quorum_xla, compile_budget=1),
    Entry("egress.ready_bundle", "planes_off", _egress_entries,
          compile_budget=1),
    Entry("egress.delta", "planes_off", _egress_entries, compile_budget=1),
    Entry("rebase.indexes", "planes_off", _rebase_entries,
          compile_budget=1),
    Entry("rebase.fabric", "planes_off", _rebase_entries, compile_budget=1),
    Entry("paged.page_in", "paged", _paged_entries, compile_budget=1),
    Entry("paged.page_out", "paged", _paged_entries, compile_budget=1),
    # the shipped drivers the original manifest never audited: the
    # mesh-blocked multi-chip driver, the ServeLoop round program, and
    # the diet+paged composed profile the capacity claims quote
    Entry("mesh.step.xla", "planes_on", _mesh_step, compile_budget=1),
    Entry("serve.round", "serve", _serve_round, compile_budget=1,
          expect_on={"metrics": True, "chaos": False, "trace": False,
                     "paged": False, "tier": False, "lease": False},
          diet=True),
    # the leader-lease plane (ISSUE 20): the serve-shaped round with the
    # lease columns riding the packed scan carry; every OTHER entry
    # asserts "lease": False under its pinned-off profile — the
    # RAFT_TPU_LEASE=0 full-elision claim the ledger's bytes/lane rows
    # corroborate
    Entry("round.xla.lease", "lease", _round_xla_lease, compile_budget=1,
          expect_on=_LEASE_ON, diet=True),
    Entry("round.xla.diet_paged", "diet_paged", _round_diet_paged,
          compile_budget=1,
          expect_on={"metrics": True, "chaos": False, "trace": False,
                     "paged": True, "tier": False, "lease": False},
          diet=True),
    # the in-kernel paged megakernel (ISSUE 17): page_in/page_out fused
    # into the K=2 pallas grid over two lane tiles — elision, capture,
    # donation, and carry stability all audited with the pool/pt riding
    # the scan carry instead of the dispatch boundary
    Entry("round.pallas.paged_inkernel", "paged_inkernel",
          _round_pallas_inkernel, compile_budget=1,
          expect_on={"metrics": True, "chaos": False, "trace": False,
                     "paged": True, "tier": False, "lease": False}),
    # the hot/cold tier's dispatch-boundary pair (tier/engine.py): the
    # evict-snapshot gather and the donating admit-restore scatter; every
    # OTHER entry above asserts "tier": False under its pinned-off
    # profile — the RAFT_TPU_TIER=0 full-elision claim
    Entry("tier.gather", "tier", _tier_entries, compile_budget=1,
          expect_on=_TIER_ON),
    Entry("tier.scatter", "tier", _tier_entries, compile_budget=1,
          expect_on=_TIER_ON),
    # the cross-host fabric's per-round boundary pair (ISSUE 18): the
    # O(active) outbound gather-and-clear and the capped inbound scatter
    # — each produces the fabric carry the next donated round consumes
    Entry("fabric.extract", "fabric", _fabric_entries, compile_budget=1),
    Entry("fabric.inject", "fabric", _fabric_entries, compile_budget=1),
)


def entry_names():
    return tuple(e.name for e in ENTRIES)


def build_records():
    """Materialize every manifest entry under its env profile. Returns
    [(entry, record)] with exactly one record per Entry: builders that
    return several records (the shared egress/rebase/paged builders)
    are keyed back to their row by record name. Builders run once per
    (profile, build) pair so shared builders construct one cluster."""
    built = {}
    out = []
    for e in ENTRIES:
        key = (e.profile, e.build)
        if key not in built:
            with env_profile(PROFILES[e.profile]):
                built[key] = {r["name"]: r for r in e.build()}
        rec = built[key].get(e.name)
        if rec is None:
            # single-device host: the sharded builder returns no record
            continue
        out.append((e, rec))
    return out
