"""Compiled-program resource ledger: extract and gate the capacity math.

For every registry entry point, ``jit.lower(args).compile()`` (AOT — no
dispatch, no execution) and pull the compiler's own arithmetic:

- ``cost_analysis()``  -> flops, bytes accessed
- ``memory_analysis()`` -> argument/output/temp/alias bytes, code size

plus the one number XLA cannot know — the between-rounds carry bytes —
from the record's carry-leg avals. Everything is normalized to per-lane
(flow metrics additionally per-round) so the budget is shape-invariant:
the 12-lane CI geometry and a 3M-lane chip share one LEDGER.json row.

``run_ledger`` diffs the current tree against the checked-in baseline
(budgets.diff_entry owns the tolerance rules) and returns findings the
``python -m raft_tpu.analysis --ledger`` gate turns into a non-zero
exit; ``update=True`` re-baselines and reports the old->new drift
instead. The bench-facing helpers at the bottom are the ONE place
bytes-moved is computed from a lowering — benches/pallas_ab.py routes
through them so the bench and the gate can never disagree.
"""

from __future__ import annotations

from raft_tpu.analysis import budgets
from raft_tpu.analysis.jaxpr_audit import Finding, carry_leaves

__all__ = [
    "cost_metrics",
    "memory_metrics",
    "entry_metrics",
    "run_ledger",
    "bytes_accessed",
    "round_bytes_probe",
]


# --------------------------------------------------------------------------
# extraction from one compiled program


def lower_entry(rec):
    """AOT-lower a registry record exactly the way the engine dispatches
    it: the jit twin, the example args, static+plane kwargs."""
    jit = rec["jit"]
    kwargs = {**rec.get("static", {}), **rec.get("kwargs", {})}
    return jit.lower(*rec["args"], **kwargs)


def cost_metrics(compiled) -> dict:
    """Normalize ``compiled.cost_analysis()`` across its backend quirks
    (CPU returns a one-element list of dicts; some backends return the
    dict bare, some nothing)."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else None
    if not cost:
        return {}
    out = {}
    if cost.get("flops") is not None:
        out["flops"] = float(cost["flops"])
    if cost.get("bytes accessed") is not None:
        out["bytes_accessed"] = float(cost["bytes accessed"])
    return out


def memory_metrics(compiled) -> dict:
    """``compiled.memory_analysis()`` -> plain dict (CompiledMemoryStats
    fields); empty when the backend doesn't expose it."""
    try:
        mem = compiled.memory_analysis()
    except Exception:
        return {}
    if mem is None:
        return {}
    fields = (
        ("argument_size_in_bytes", "arg_bytes"),
        ("output_size_in_bytes", "out_bytes"),
        ("temp_size_in_bytes", "temp_bytes"),
        ("alias_size_in_bytes", "alias_bytes"),
        ("generated_code_size_in_bytes", "generated_code_bytes"),
    )
    out = {}
    for attr, key in fields:
        v = getattr(mem, attr, None)
        if v is not None:
            out[key] = float(v)
    return out


def carry_nbytes(rec) -> float | None:
    """Bytes of the between-rounds carry (the HBM residency the entry
    claims), from the carry-leg avals — None when the record declares no
    carry (pure kernels like quorum)."""
    leaves = carry_leaves(rec)
    if not leaves:
        return None
    return float(sum(leaf.size * leaf.dtype.itemsize for leaf in leaves))


def entry_metrics(rec) -> dict:
    """The ledger row for one record: every metric normalized per lane
    (flow metrics per round per lane), rounded so LEDGER.json diffs stay
    readable."""
    lanes = rec.get("lanes") or 1
    rounds = rec.get("rounds") or rec.get("static", {}).get("n_rounds") or 1
    compiled = lower_entry(rec).compile()
    cost = cost_metrics(compiled)
    mem = memory_metrics(compiled)
    out = {}
    cb = carry_nbytes(rec)
    if cb is not None:
        out["carry_bytes_per_lane"] = cb / lanes
    if "bytes_accessed" in cost:
        out["bytes_moved_per_round_per_lane"] = (
            cost["bytes_accessed"] / rounds / lanes
        )
    if "flops" in cost:
        out["flops_per_round_per_lane"] = cost["flops"] / rounds / lanes
    for src, dst in (
        ("arg_bytes", "arg_bytes_per_lane"),
        ("out_bytes", "out_bytes_per_lane"),
        ("temp_bytes", "temp_bytes_per_lane"),
        ("alias_bytes", "alias_bytes_per_lane"),
    ):
        if src in mem:
            out[dst] = mem[src] / lanes
    if "generated_code_bytes" in mem:
        out["generated_code_bytes"] = mem["generated_code_bytes"]
    return {k: round(v, 3) for k, v in out.items()}


# --------------------------------------------------------------------------
# the gate


def _tol_scale() -> float:
    from raft_tpu import config

    return config.env_float("RAFT_TPU_LEDGER_TOL", 1.0)


def run_ledger(pairs=None, *, update: bool = False, path: str | None = None,
               tol_scale: float | None = None) -> tuple[list, dict]:
    """Measure every registry entry and diff against LEDGER.json.

    Returns (findings, report). ``update=True`` writes the new baseline
    instead of failing, still reporting the old->new rows so the caller
    can print a human-readable re-baseline diff. ``pairs`` lets the gate
    reuse records already built by the audit step (one build, two
    passes)."""
    import jax

    if pairs is None:
        from raft_tpu.analysis.registry import build_records

        pairs = build_records()
    path = path or budgets.default_ledger_path()
    scale = _tol_scale() if tol_scale is None else tol_scale
    tols = budgets.scaled_tolerances(scale)
    meta = {"backend": jax.default_backend(), "jax": jax.__version__}

    current = {}
    cap_findings: list = []
    for entry, rec in pairs:
        cur = entry_metrics(rec)
        current[entry.name] = cur
        # optional per-record absolute cap (rec["temp_cap_per_lane"]):
        # unlike the baseline diff, this budget holds even across a
        # re-baseline — the registry record owns the number, so
        # --update-ledger can never quietly ratify a regression
        cap = rec.get("temp_cap_per_lane")
        if cap is not None and cur.get("temp_bytes_per_lane", 0.0) > cap:
            cap_findings.append(Finding(entry.name, "ledger", (
                f"temp_bytes_per_lane={cur['temp_bytes_per_lane']} exceeds "
                f"the record's hard cap {cap} — a full-window [N, W] "
                "temporary (or an allocation of that class) crept back "
                "into the compiled program"
            )))

    report = {
        "path": path,
        "meta": meta,
        "entries": sorted(current),
        "tol_scale": scale,
        "updated": update,
        "diff": "",
    }
    findings: list = list(cap_findings)
    per_entry_rows: dict = {}

    baseline = budgets.load_ledger(path)
    if update:
        old = (baseline or {}).get("entries", {})
        for name, cur in current.items():
            _, rows = budgets.diff_entry(name, old.get(name, {}), cur,
                                         tols=tols)
            per_entry_rows[name] = rows
        budgets.save_ledger(path, meta, current)
        report["diff"] = budgets.render_diff(per_entry_rows)
        return list(cap_findings), report

    if baseline is None:
        findings.append(Finding("LEDGER.json", "ledger", (
            f"no baseline at {path} — run "
            "`python -m raft_tpu.analysis --update-ledger` and check the "
            "result in"
        )))
        report["diff"] = "(no baseline)\n"
        return findings, report

    metrics = None
    if baseline.get("meta", {}).get("backend") != meta["backend"]:
        # a cpu baseline says nothing about a tpu cost model; the
        # aval-determined metrics still transfer
        metrics = budgets.AVAL_METRICS
        report["cross_backend"] = True

    base_entries = baseline.get("entries", {})
    for name, cur in current.items():
        if name not in base_entries:
            findings.append(Finding(name, "ledger", (
                "entry has no LEDGER.json baseline — new entry point; "
                "run --update-ledger to budget it"
            )))
            per_entry_rows[name] = [
                (k, None, v, "new") for k, v in sorted(cur.items())
            ]
            continue
        fs, rows = budgets.diff_entry(
            name, base_entries[name], cur, tols=tols, metrics=metrics
        )
        findings += fs
        per_entry_rows[name] = rows
    for name in sorted(set(base_entries) - set(current)):
        findings.append(Finding(name, "ledger", (
            "LEDGER.json budgets an entry the registry no longer builds "
            "— stale baseline row; run --update-ledger"
        )))
    report["diff"] = budgets.render_diff(per_entry_rows)
    return findings, report


# --------------------------------------------------------------------------
# bench-facing helpers (the one shared bytes-moved computation)


def bytes_accessed(compiled) -> float | None:
    """Total bytes accessed per dispatch from XLA cost analysis; None on
    backends without a cost model."""
    return cost_metrics(compiled).get("bytes_accessed")


def round_bytes_probe(cluster, rounds: int, **overrides) -> float | None:
    """Bytes accessed PER ROUND of a cluster's compiled round program —
    the exact computation the ledger gate budgets, exported so benches
    (benches/pallas_ab.py) report the same number the gate enforces.
    Lowers the copying twin (donation doesn't change bytes accessed and
    the nodonate lowering never warns about example-arg reuse)."""
    try:
        lowered = cluster.lower_round_program(
            rounds, donate=False, **overrides
        )
        return_val = bytes_accessed(lowered.compile())
    except Exception:
        return None
    if return_val is None:
        return None
    return return_val / rounds
