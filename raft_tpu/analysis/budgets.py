"""Resource-budget schema for the compiled-program ledger.

The ledger (analysis/ledger.py) extracts per-entry resource metrics from
every registry entry point's compiled program — XLA ``cost_analysis``
(flops, bytes accessed) and ``memory_analysis`` (argument/output/temp/
alias bytes, generated code size) — normalized to shape-invariant
per-lane (and per-round, for the flow metrics) numbers so the same
budget holds at 12 lanes on CPU CI and at 3M lanes on a chip. This
module owns everything about those numbers EXCEPT their extraction:

- the metric schema (names, which are hard, which direction fails),
- per-metric tolerances and the ``RAFT_TPU_LEDGER_TOL`` scaling rule,
- the LEDGER.json load/save format,
- the baseline diff (``diff_entry``) and its human rendering.

No jax import here: budget arithmetic must be loadable by tooling (and
the seeded-regression tests) without touching a backend.

LEDGER.json format (version 1)::

    {
      "version": 1,
      "meta": {"backend": "cpu", "jax": "0.4.37"},
      "entries": {
        "round.xla": {"carry_bytes_per_lane": 199.0, ...},
        ...
      }
    }

Metric semantics:

- ``carry_bytes_per_lane`` — bytes of the between-rounds carry (the HBM
  residency claim) per lane, from the record's carry-leg avals. HARD:
  growth past tolerance fails regardless of RAFT_TPU_LEDGER_TOL; this is
  the diet's 38% and the paged window's savings, the north-star number.
- ``temp_bytes_per_lane`` — XLA temp allocations per lane. HARD: a new
  temp buffer is a silent HBM tax per dispatch.
- ``arg_bytes_per_lane`` / ``out_bytes_per_lane`` — the program's
  argument/result footprint per lane (aval-determined, so the tolerance
  is essentially zero).
- ``alias_bytes_per_lane`` — donated bytes aliased in-place per lane.
  FLOOR metric: this one fails on *shrink* (a dropped donation alias is
  an HBM doubling); growth is an improvement.
- ``bytes_moved_per_round_per_lane`` / ``flops_per_round_per_lane`` —
  cost-analysis flow metrics, normalized per round per lane.
- ``generated_code_bytes`` — absolute executable size (not per-lane);
  the loosest tolerance, it exists to catch code-size explosions.
"""

from __future__ import annotations

import dataclasses
import json
import os

from raft_tpu.analysis.jaxpr_audit import Finding

LEDGER_VERSION = 1

# metrics whose value is fully determined by avals (not by the backend's
# cost model) — the only ones compared when the baseline was produced on
# a different backend than the current run
AVAL_METRICS = (
    "carry_bytes_per_lane",
    "arg_bytes_per_lane",
    "out_bytes_per_lane",
)


@dataclasses.dataclass(frozen=True)
class Tolerance:
    """Failure rule for one metric. ``grow`` direction fails when
    ``cur > base * (1 + rel) + atol``; ``shrink`` fails when
    ``cur < base * (1 - rel) - atol``. ``hard`` metrics ignore the
    RAFT_TPU_LEDGER_TOL multiplier — their budget is the contract."""

    rel: float = 0.0
    atol: float = 0.0
    hard: bool = False
    direction: str = "grow"  # "grow" | "shrink"

    def scaled(self, scale: float) -> "Tolerance":
        if self.hard or scale == 1.0:
            return self
        return dataclasses.replace(
            self, rel=self.rel * scale, atol=self.atol * scale
        )


# schema order is render order
TOLERANCES = {
    "carry_bytes_per_lane": Tolerance(rel=0.0, atol=0.5, hard=True),
    "temp_bytes_per_lane": Tolerance(rel=0.0, atol=2.0, hard=True),
    "arg_bytes_per_lane": Tolerance(rel=0.0, atol=0.5, hard=True),
    "out_bytes_per_lane": Tolerance(rel=0.0, atol=0.5, hard=True),
    "alias_bytes_per_lane": Tolerance(
        rel=0.0, atol=0.5, hard=True, direction="shrink"
    ),
    "bytes_moved_per_round_per_lane": Tolerance(rel=0.05, atol=64.0),
    "flops_per_round_per_lane": Tolerance(rel=0.05, atol=64.0),
    "generated_code_bytes": Tolerance(rel=0.5, atol=16384.0),
}


def scaled_tolerances(scale: float) -> dict:
    """Apply the RAFT_TPU_LEDGER_TOL multiplier to every SOFT metric's
    tolerance; hard budgets (carry, temps, interface bytes, aliases)
    never loosen."""
    return {k: t.scaled(scale) for k, t in TOLERANCES.items()}


def repo_root() -> str:
    return os.path.dirname(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )


def default_ledger_path() -> str:
    from raft_tpu import config

    return config.env_str(
        "RAFT_TPU_LEDGER_PATH", os.path.join(repo_root(), "LEDGER.json")
    )


def load_ledger(path: str) -> dict | None:
    if not os.path.exists(path):
        return None
    with open(path) as fh:
        data = json.load(fh)
    if data.get("version") != LEDGER_VERSION:
        raise ValueError(
            f"{path}: ledger version {data.get('version')!r}, this tree "
            f"speaks {LEDGER_VERSION} — regenerate with --update-ledger"
        )
    return data


def save_ledger(path: str, meta: dict, entries: dict) -> None:
    data = {
        "version": LEDGER_VERSION,
        "meta": meta,
        "entries": {
            name: {k: entries[name][k] for k in sorted(entries[name])}
            for name in sorted(entries)
        },
    }
    with open(path, "w") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")


def _exceeds(base: float, cur: float, tol: Tolerance) -> bool:
    if tol.direction == "shrink":
        return cur < base * (1.0 - tol.rel) - tol.atol
    return cur > base * (1.0 + tol.rel) + tol.atol


def diff_entry(name: str, baseline: dict, current: dict,
               tols: dict | None = None,
               metrics: tuple | None = None) -> tuple[list, list]:
    """Diff one entry's current metrics against its baseline. Returns
    (findings, rows); rows are (metric, base, cur, status) for the human
    rendering, status in {"ok", "FAIL", "improved", "new", "gone"}.
    ``metrics`` restricts the comparison (the cross-backend case)."""
    tols = tols or TOLERANCES
    out, rows = [], []
    keys = [k for k in tols if k in baseline or k in current]
    if metrics is not None:
        keys = [k for k in keys if k in metrics]
    for k in keys:
        base, cur = baseline.get(k), current.get(k)
        if base is None:
            rows.append((k, None, cur, "new"))
            out.append(Finding(name, "ledger", (
                f"metric {k}={cur} has no baseline in LEDGER.json — the "
                "entry grew a new resource class; review it and run "
                "--update-ledger"
            )))
            continue
        if cur is None:
            rows.append((k, base, None, "gone"))
            out.append(Finding(name, "ledger", (
                f"baseline metric {k}={base} is no longer measured — "
                "stale budget row; run --update-ledger"
            )))
            continue
        tol = tols[k]
        if _exceeds(base, cur, tol):
            rows.append((k, base, cur, "FAIL"))
            verb = "shrank" if tol.direction == "shrink" else "grew"
            kind = "hard budget" if tol.hard else "budget"
            out.append(Finding(name, "ledger", (
                f"{k} {verb} past its {kind}: {base} -> {cur} "
                f"(rel={tol.rel}, atol={tol.atol})"
            )))
        elif _exceeds(cur, base, dataclasses.replace(
                tol, direction="shrink" if tol.direction == "grow"
                else "grow")):
            # moved the GOOD way past tolerance: not a failure, but the
            # baseline is stale enough to hide a future regression
            rows.append((k, base, cur, "improved"))
        else:
            rows.append((k, base, cur, "ok"))
    return out, rows


def render_diff(per_entry_rows: dict) -> str:
    """Human-readable ledger diff: one block per entry, one line per
    metric, only entries with at least one non-"ok" row are expanded."""
    lines = []
    for name in sorted(per_entry_rows):
        rows = per_entry_rows[name]
        interesting = [r for r in rows if r[3] != "ok"]
        if not interesting:
            lines.append(f"{name}: ok ({len(rows)} metric(s))")
            continue
        lines.append(f"{name}:")
        for metric, base, cur, status in rows:
            def _fmt(v):
                return "-" if v is None else f"{v:g}"
            delta = ""
            if isinstance(base, (int, float)) and isinstance(
                    cur, (int, float)) and base:
                delta = f" ({(cur - base) / base:+.1%})"
            lines.append(
                f"  {status:>8}  {metric}: {_fmt(base)} -> "
                f"{_fmt(cur)}{delta}"
            )
    return "\n".join(lines) + "\n"
