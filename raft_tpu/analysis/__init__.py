"""Static program auditor: jaxpr/HLO invariant checks, a recompile
sentinel, and the repo lint gate.

- ``registry`` — declarative manifest of every compiled entry point
  (env profile, invariants, compile budget per entry).
- ``jaxpr_audit`` — elision / donation / dtype-discipline / constant-
  capture / host-hygiene checks over traced jaxprs and lowered HLO;
  purely static (make_jaxpr + jit.lower, nothing executes).
- ``recompile`` — two-pass compile-watch sentinel over the canonical
  bench smoke: per-entry warmup budgets, zero steady-state compiles.
- ``lint`` — AST rules: RAFT_TPU_* env reads must route through
  config.py, knobs must cross-check against README's env tables, and
  host-plane modules stay off the device outside resolve points.

``python -m raft_tpu.analysis`` runs all of it and emits ANALYSIS.json
(wired into runtests.sh as the static chunk before the serial ladder).

Import note: this ``__init__`` intentionally imports no submodule —
``python -m raft_tpu.analysis`` runs it before ``__main__``, and
``__main__`` must pin JAX_PLATFORMS/XLA_FLAGS before anything pulls
jax in.
"""

__all__ = ["jaxpr_audit", "lint", "recompile", "registry"]
