"""Compile-watch sentinel: catch silent recompiles of the entry points.

jax 0.4.37 logs one line per fresh XLA compilation when
``jax_log_compiles`` is on::

    Finished XLA compilation of jit(fused_rounds) in 0.41 sec

(logger ``jax._src.dispatch``, WARNING level). The sentinel attaches a
handler there, drives the canonical bench smoke twice with the SAME
cluster objects, and holds two invariants:

- **warmup**: each manifest entry point compiles at most its
  ``compile_budget`` (one signature per entry in the smoke, so the
  budget is 1 everywhere — a second compile means something in the
  dispatch path perturbs the jit signature per call: a python-scalar
  static that changes, a re-wrapped closure, a fresh jit object).
- **steady**: a second pass over the same smoke compiles NOTHING.
  Every re-dispatch must hit the in-memory executable cache; one fresh
  compile here is the classic perf cliff (a per-call lambda, an
  unhashable static, an aval flip like weak_type drift).

Unbudgeted compile names (eager-op jits, init-time packing helpers) are
collected but never fail the run — they're reported so a new entry
point showing up here is visible before someone adds it to the
manifest.
"""

from __future__ import annotations

import logging
import re

from raft_tpu.analysis.jaxpr_audit import Finding

# "Finished XLA compilation of jit(fused_rounds) in 0.41 sec"
_COMPILE_RE = re.compile(r"Finished XLA compilation of (.+?) in [0-9.eE+-]+ sec")
_JIT_NAME_RE = re.compile(r"jit\(([^)]*)\)")

# manifest entry -> the exact jit names its dispatch path may compile
ENTRY_JIT_NAMES = {
    "round.xla": ("fused_rounds",),
    "round.pallas": ("pallas_rounds",),
    "sharded.step.xla": ("stepper",),
    "quorum.pallas": ("joint_committed_pallas", "committed_pallas"),
    "quorum.xla": ("joint_committed",),
    "egress.ready_bundle": ("ready_bundle",),
    "egress.delta": ("delta_bundle",),
    "rebase.indexes": ("_rebase_indexes",),
    "rebase.fabric": ("_rebase_fabric",),
    "paged.page_in": ("page_in_host", "page_in"),
    "paged.page_out": ("page_out_host", "page_out"),
    "tier.gather": ("_tier_gather",),
    "tier.scatter": ("_tier_scatter",),
}


class CompileWatch(logging.Handler):
    """Counts fresh XLA compilations per jit name while attached."""

    def __init__(self):
        super().__init__(level=logging.WARNING)
        self.counts: dict[str, int] = {}

    def emit(self, record):
        m = _COMPILE_RE.search(record.getMessage())
        if not m:
            return
        jm = _JIT_NAME_RE.search(m.group(1))
        name = jm.group(1) if jm else m.group(1)
        self.counts[name] = self.counts.get(name, 0) + 1

    def reset(self):
        self.counts = {}

    def __enter__(self):
        import jax

        self._prev = jax.config.jax_log_compiles
        jax.config.update("jax_log_compiles", True)
        self._logger = logging.getLogger("jax._src.dispatch")
        # the compile log is our signal, not the operator's: keep the
        # firehose (dispatch's "Finished ..." lines and pxla's
        # "Compiling ..." companions) out of stderr while the watch owns it
        self._pxla = logging.getLogger("jax._src.interpreters.pxla")
        self._propagate = (self._logger.propagate, self._pxla.propagate)
        self._logger.propagate = False
        self._pxla.propagate = False
        # a handler-less non-propagating logger falls through to
        # logging.lastResort (stderr): park a NullHandler on pxla
        self._null = logging.NullHandler()
        self._pxla.addHandler(self._null)
        self._logger.addHandler(self)
        return self

    def __exit__(self, *exc):
        self._logger.removeHandler(self)
        self._pxla.removeHandler(self._null)
        self._logger.propagate, self._pxla.propagate = self._propagate
        import jax

        jax.config.update("jax_log_compiles", False if not self._prev else True)
        return False


def _bucket(counts: dict) -> tuple[dict, dict]:
    """Split raw jit-name counts into (per-entry counts, untracked)."""
    per_entry = {e: 0 for e in ENTRY_JIT_NAMES}
    untracked = {}
    owner = {}
    for entry, names in ENTRY_JIT_NAMES.items():
        for n in names:
            owner[n] = entry
    for name, c in counts.items():
        e = owner.get(name)
        if e is None:
            untracked[name] = untracked.get(name, 0) + c
        else:
            per_entry[e] += c
    return per_entry, untracked


def _smoke_context():
    """Build the canonical smoke's clusters and operands ONCE — steady
    state only holds if the second pass reuses the same objects (a fresh
    ShardedFusedCluster owns a fresh stepper jit; a fresh jax.jit
    wrapper is a fresh cache)."""
    import jax
    import numpy as np
    import jax.numpy as jnp

    from raft_tpu.analysis.registry import PROFILES, env_profile
    from raft_tpu.ops import quorum as qr
    from raft_tpu.ops import quorum_pallas as qp
    from raft_tpu.ops import ready_mask as rm
    from raft_tpu.ops import paged as pgmod
    from raft_tpu.ops import fused as fmod
    from raft_tpu.state import unpack_state

    ctx = {}
    with env_profile(PROFILES["planes_on"]):
        ctx["xla"] = fmod.FusedCluster(n_groups=4, n_voters=3, engine="xla")
        ctx["pallas"] = fmod.FusedCluster(
            n_groups=4, n_voters=3, engine="pallas", rounds_per_call=2
        )
        if len(jax.devices()) >= 2:
            from raft_tpu.parallel.sharded import ShardedFusedCluster

            ctx["sharded"] = ShardedFusedCluster(n_groups=16, n_voters=3)
    with env_profile(PROFILES["paged"]):
        ctx["paged"] = fmod.FusedCluster(n_groups=4, n_voters=3, engine="xla")

    rng = np.random.default_rng(3)
    n, v = 256, 3
    ctx["match"] = jnp.asarray(rng.integers(0, 1 << 20, (n, v)), jnp.int32)
    ctx["m_in"] = jnp.asarray(rng.random((n, v)) < 0.8)
    ctx["m_out"] = jnp.asarray(rng.random((n, v)) < 0.4)
    ctx["quorum_xla_jit"] = jax.jit(qr.joint_committed)

    cl = ctx["xla"]
    nl = cl.state.term.shape[0]
    z = np.zeros((nl,), np.int32)
    f = np.zeros((nl,), bool)
    ctx["host"] = rm.HostCursors(
        prev_term=z, prev_vote=z, prev_commit=z, prev_lead=z,
        prev_state=z, host_pending=f, is_async=f, inprog=z,
        snap_inprog=z, applying=z,
    )
    # rebase operands come from a cluster that never dispatches: unpack/
    # fat-fabric pass already-wide leaves through by reference, and the
    # donating smoke clusters delete their pre-dispatch buffers
    with env_profile(PROFILES["planes_off"]):
        base = fmod.FusedCluster(n_groups=4, n_voters=3, engine="xla")
    st = unpack_state(base.state)
    ctx["rebase_args"] = (
        st,
        jnp.asarray(np.ones((nl,), bool)),
        jnp.asarray(np.zeros((nl,), np.int32)),
    )
    ctx["rebase_fab"] = fmod.fat_fabric(fmod.unpack_fabric(base.fab))
    # tier gather/scatter operands: one group's voter lanes pow2-padded,
    # rows sliced host-side so building them compiles nothing
    from raft_tpu.tier import engine as tmod

    with env_profile(PROFILES["tier"]):
        tcl = fmod.FusedCluster(n_groups=4, n_voters=3, engine="xla")
    tst = unpack_state(tcl.state)
    tfb = fmod.unpack_fabric(tcl.fab)
    tlanes, _ = tmod._pad_rows(np.arange(tcl.v, dtype=np.int32), None)
    trows = lambda t: jax.tree.map(
        lambda x: jnp.asarray(np.asarray(x)[tlanes]), t
    )
    ctx["tier_args"] = (tst, tfb, jnp.asarray(tlanes))
    ctx["tier_rows"] = (trows(tst), trows(tfb))
    ctx["tmod"] = tmod
    ctx["rm"] = rm
    ctx["qp"] = qp
    ctx["fmod"] = fmod
    ctx["pgmod"] = pgmod
    return ctx


def _drive(ctx):
    """One pass of the canonical smoke: every manifest entry point
    dispatches once with one fixed signature."""
    import jax

    rm, qp, fmod, pgmod = ctx["rm"], ctx["qp"], ctx["fmod"], ctx["pgmod"]
    ctx["xla"].run(2)
    ctx["pallas"].run(2)
    if "sharded" in ctx:
        ctx["sharded"].run(2)
    qp.joint_committed_pallas(
        ctx["match"], ctx["m_in"], ctx["m_out"], interpret=True
    )
    ctx["quorum_xla_jit"](ctx["match"], ctx["m_in"], ctx["m_out"])
    rm.compute_bundle(ctx["xla"].state, ctx["host"])
    rm.compute_delta(ctx["xla"].state, None)
    st, mask, delta = ctx["rebase_args"]
    jax.block_until_ready(fmod._rebase_indexes_jit(st, mask, delta))
    jax.block_until_ready(fmod.rebase_fabric(ctx["rebase_fab"], delta))
    pg = ctx["paged"]
    full, _ = pgmod.page_in_host(pg.state, pg.paged)
    jax.block_until_ready(pgmod.page_out_host(full, pg.paged))
    # the tier pair via the copying scatter twin (same jit name as the
    # donating one, and the operands stay valid for the steady pass)
    tg, tsc, _ = ctx["tmod"]._jits()
    tst, tfb, tlanes = ctx["tier_args"]
    jax.block_until_ready(tg(tst, tfb, tlanes))
    jax.block_until_ready(tsc(tst, tfb, tlanes, *ctx["tier_rows"]))


def run_sentinel() -> tuple[list, dict]:
    """Run the two-pass compile sentinel. Returns (findings, report):
    findings is the Finding list (empty = clean), report carries the
    per-phase per-entry compile counts for ANALYSIS.json."""
    from raft_tpu.analysis.registry import ENTRIES

    budgets = {
        e.name: e.compile_budget
        for e in ENTRIES
        if e.name in ENTRY_JIT_NAMES
    }
    findings = []
    with CompileWatch() as watch:
        ctx = _smoke_context()
        watch.reset()  # construction-time eager compiles are not the smoke
        _drive(ctx)
        warm, warm_untracked = _bucket(watch.counts)
        watch.reset()
        _drive(ctx)
        steady, steady_untracked = _bucket(watch.counts)

    driven = set(warm) if "sharded" in ctx else set(warm) - {"sharded.step.xla"}
    for entry in sorted(driven):
        budget = budgets.get(entry, 1)
        if warm[entry] > budget:
            findings.append(Finding(entry, "recompile", (
                f"warmup compiled {warm[entry]}x (budget {budget}) — the "
                "dispatch path perturbs the jit signature per call"
            )))
        if warm[entry] == 0:
            findings.append(Finding(entry, "recompile", (
                "the smoke never compiled this entry point — the sentinel "
                "lost coverage of it (smoke and manifest drifted)"
            )))
        if steady.get(entry, 0) > 0:
            findings.append(Finding(entry, "recompile", (
                f"steady-state re-run compiled {steady[entry]}x — a warm "
                "re-dispatch missed the executable cache (per-call "
                "closure, unhashable static, or aval drift)"
            )))
    report = {
        "warmup": warm,
        "warmup_untracked": warm_untracked,
        "steady": steady,
        "steady_untracked": steady_untracked,
    }
    return findings, report
