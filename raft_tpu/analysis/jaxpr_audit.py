"""Static jaxpr/HLO invariant checks over the registry's entry points.

Every check here runs WITHOUT executing a round: programs are traced with
`jax.make_jaxpr` and lowered with `jit.lower(...)`, never called. The
checks encode the compile-time contracts the rest of the repo asserts
ad-hoc in whichever test first needed them (see ISSUE/README):

- **elision** — with a plane's env knob off, zero primitives attributable
  to that plane anywhere in the program. Proven via the shared trace-time
  CallCounters (raft_tpu/testing/counters.py): the plane's device fn bumps
  its counter when TRACED, so a flat counter across the `make_jaxpr` of an
  entry point means the plane contributed nothing to the jaxpr. A plane
  that is ON must bump (positive sanity — a counter that never moves
  can't prove elision).
- **donation** — a donating twin's lowering must carry an input-output
  alias for every donated carry leaf; a donated leaf that LOST its alias
  (jax lowers it with a "donated buffers were not usable" warning and no
  `tf.aliasing_output` attribute) is a silent HBM doubling. The copying
  twin must alias nothing.
- **dtype discipline** — under RAFT_TPU_DIET=1 the packed carry columns
  (uint16 indexes/terms, int8 ids, int16 sizes, uint8/16/32 bitsets) must
  ride the scan carry / pallas operands in their packed dtypes. The
  in-body widen/compute/narrow cycle is by design; what must never happen
  is a packed column riding the BETWEEN-rounds carry widened to int32 —
  so the check asserts every narrow leaf of the actual carry appears
  among the program's scan-carry/kernel-operand avals.
- **constant capture** — no jaxpr consts feeding a `pallas_call` (the
  jax 0.4.37 lifted-literal hazard from PR 4: enum scalars and array
  literals become constvars that Mosaic rejects or bakes into the
  kernel), and no large (>16 KiB) const anywhere in the program (a
  captured table silently re-uploads per executable).
- **host-boundary hygiene** — no host callbacks/infeed/outfeed inside a
  round-dispatch program: the round must be pure device code; a stray
  `debug_callback`/`pure_callback` forces a host sync per dispatch.
- **carry stability** — the between-rounds carry is a FIXPOINT: every
  carry leaf (the record's `carry_argnums`/`carry_argnames` legs) must
  come back out of the program with the identical (shape, dtype) aval.
  A silent uint16→int32 widen between rounds would quietly undo the
  diet's savings while every value-level test stays green.
- **donation escape** — the per-leaf refinement of the donation check:
  parse the lowered @main signature and name exactly WHICH donated leaf
  lost its `tf.aliasing_output` alias (the count check says how many;
  this one says which column, which is what you need to fix it).
- **paged roundtrip** — `page_out(full) -> (resident, paged)` and
  `page_in(resident, paged) -> (full, paged)` must be aval-inverse:
  each one's outputs match the other's inputs leaf for leaf, so the
  host boundary can cycle the window forever without a reshape/upcast
  creeping in (records declare the pairing via a `roundtrip` key).
"""

from __future__ import annotations

import dataclasses
import functools
import inspect
import re
import warnings

import jax

from raft_tpu.testing import counters as ctr

# dtypes the diet-v2 pack boundary may produce; anything in the carry with
# one of these is a "packed column" the program must preserve
NARROW_DTYPES = ("uint8", "uint16", "uint32", "int8", "int16")

# one const bigger than this anywhere in a program is a capture bug (the
# engine passes all real data as arguments; consts should be iota/scalars)
MAX_CONST_BYTES = 16 * 1024

# primitives that cross the host boundary inside a device program
_HOST_PRIMS = ("infeed", "outfeed")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation. `entry` names the manifest entry point,
    `check` the auditor pass, `detail` the human-readable evidence."""

    entry: str
    check: str
    detail: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------------
# program tracing


def trace_entry(rec) -> "jax.core.ClosedJaxpr":
    """Trace a registry program record to its closed jaxpr without
    executing it. Static kwargs close over the fn; array args/kwargs are
    passed as tracer arguments so real data never becomes a jaxpr const
    (which would defeat the constant-capture check)."""
    fn = functools.partial(rec["fn"], **rec.get("static", {}))
    return jax.make_jaxpr(fn)(*rec["args"], **rec.get("kwargs", {}))


def traced_counter_deltas(rec) -> tuple["jax.core.ClosedJaxpr", dict]:
    """(closed_jaxpr, {plane: trace-time counter delta}) for one record."""
    before = ctr.snapshot()
    jaxpr = trace_entry(rec)
    after = ctr.snapshot()
    return jaxpr, {k: after[k] - before.get(k, 0) for k in after}


# --------------------------------------------------------------------------
# jaxpr walking


def iter_jaxprs(jaxpr):
    """Yield (jaxpr, constvar_set) for the top jaxpr and every sub-jaxpr
    reachable through eqn params (scan/cond/pjit/pallas bodies)."""
    seen = set()

    def walk(jx):
        if id(jx) in seen:
            return
        seen.add(id(jx))
        yield jx, set(jx.constvars)
        for eqn in jx.eqns:
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub)

    yield from walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        for item in v if isinstance(v, (tuple, list)) else (v,):
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr  # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item  # raw Jaxpr


def iter_eqns(jaxpr):
    for jx, _ in iter_jaxprs(jaxpr):
        yield from jx.eqns


def _aval_key(aval) -> tuple:
    return (tuple(aval.shape), str(aval.dtype))


def storage_avals(jaxpr) -> set:
    """The program's "storage" avals: scan-carry avals (what HBM holds
    between rounds) plus pallas_call operand avals (what the kernel is
    fed). These are the positions where the diet's packed dtypes must
    survive."""
    out = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "scan":
            nc = eqn.params.get("num_consts", 0)
            ncar = eqn.params.get("num_carry", 0)
            body = eqn.params.get("jaxpr")
            invars = body.jaxpr.invars if hasattr(body, "jaxpr") else body.invars
            for v in invars[nc : nc + ncar]:
                out.add(_aval_key(v.aval))
        elif name == "pallas_call":
            for v in eqn.invars:
                if hasattr(v, "aval"):
                    out.add(_aval_key(v.aval))
    return out


def narrow_carry_avals(tree) -> set:
    """The (shape, dtype) set of every packed-dtype leaf in an actual
    carry pytree — what the program's storage avals must cover."""
    out = set()
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "dtype") and str(leaf.dtype) in NARROW_DTYPES:
            out.add((tuple(leaf.shape), str(leaf.dtype)))
    return out


# --------------------------------------------------------------------------
# checks (each returns a list of Findings)


def check_elision(name, deltas, expect_on: dict) -> list:
    """expect_on: {plane: bool} — planes expected IN the program must have
    bumped their trace-time counter during the trace; planes expected OFF
    must not have."""
    out = []
    for plane, on in expect_on.items():
        d = deltas.get(plane, 0)
        if on and d <= 0:
            out.append(Finding(name, "elision", (
                f"plane '{plane}' is enabled but its device fn was never "
                "traced into the program (counter flat) — the plane "
                "silently dropped out"
            )))
        if not on and d > 0:
            out.append(Finding(name, "elision", (
                f"plane '{plane}' is disabled but its device fn was traced "
                f"{d}x into the program — elision is broken, the knob no "
                "longer compiles the plane out"
            )))
    return out


def check_dtype_discipline(name, jaxpr, carry) -> list:
    """Every packed (narrow-dtype) leaf of the real carry must appear among
    the program's scan-carry / pallas-operand avals with its packed shape
    and dtype. A missing one means some path widened it (usually to int32)
    for the ride between rounds — the silent byte-diet regression."""
    have = storage_avals(jaxpr)
    if not have:
        return []  # no scan/kernel in this program — nothing rides a carry
    out = []
    for shape, dtype in sorted(narrow_carry_avals(carry)):
        if (shape, dtype) not in have:
            out.append(Finding(name, "dtype", (
                f"packed carry column {dtype}{list(shape)} does not appear "
                "in any scan carry / kernel operand — a cast widened it "
                "between rounds (diet regression)"
            )))
    return out


def check_constant_capture(name, jaxpr) -> list:
    out = []
    for jx, constvars in iter_jaxprs(jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name != "pallas_call":
                continue
            for v in eqn.invars:
                if not hasattr(v, "aval"):
                    continue
                if v in constvars:
                    out.append(Finding(name, "capture", (
                        f"pallas_call operand {v.aval.str_short()} is a "
                        "lifted jaxpr const (captured closure/enum "
                        "constant) — pass it as an argument or register "
                        "the literal (types.register_literal_enums)"
                    )))
    top = jaxpr if hasattr(jaxpr, "consts") else None
    if top is not None:
        for c in top.consts:
            nbytes = getattr(c, "nbytes", 0)
            if nbytes > MAX_CONST_BYTES:
                out.append(Finding(name, "capture", (
                    f"program captures a {nbytes}-byte const "
                    f"{getattr(c, 'dtype', '?')}{list(getattr(c, 'shape', ()))}"
                    " — real data must ride as an argument, not a closure"
                )))
    return out


def check_host_hygiene(name, jaxpr) -> list:
    out = []
    for eqn in iter_eqns(jaxpr):
        pname = eqn.primitive.name
        if "callback" in pname or pname in _HOST_PRIMS:
            out.append(Finding(name, "hygiene", (
                f"primitive '{pname}' inside the round-dispatch program — "
                "a host round-trip per dispatch; move it to the host plane "
                "or behind a stream drain"
            )))
    return out


def carry_leaves(rec) -> list:
    """The record's between-rounds carry legs as flat leaves, in program
    order (positional carry args first, then the carry kwargs in their
    declared order). Falls back to the donation signature when a record
    predates the explicit carry metadata — for the engine twins the
    donated legs ARE the carry."""
    argnums = rec.get("carry_argnums")
    argnames = rec.get("carry_argnames")
    if argnums is None and argnames is None:
        argnums = rec.get("donate_argnums", ())
        argnames = rec.get("donate_argnames", ())
    legs = [rec["args"][i] for i in (argnums or ())]
    kw = rec.get("kwargs", {})
    legs += [kw.get(k) for k in (argnames or ())]
    return jax.tree.leaves(legs)


def check_carry_stability(name, jaxpr, rec) -> list:
    """Carry-in avals must equal the program's leading out avals leaf for
    leaf — the fused round, the rebase jits and the sharded stepper all
    return their carry first, in argument order, so a positional prefix
    compare proves the fixpoint."""
    ins = [(tuple(leaf.shape), str(leaf.dtype))
           for leaf in carry_leaves(rec)]
    if not ins:
        return []
    outs = [(tuple(a.shape), str(a.dtype)) for a in jaxpr.out_avals]
    if len(outs) < len(ins):
        return [Finding(name, "carry", (
            f"program returns {len(outs)} leaves but the carry has "
            f"{len(ins)} — the round no longer round-trips its own carry"
        ))]
    out = []
    for idx, (want, got) in enumerate(zip(ins, outs)):
        if want != got:
            out.append(Finding(name, "carry", (
                f"carry leaf {idx} enters as {want[1]}{list(want[0])} but "
                f"exits as {got[1]}{list(got[0])} — the carry fixpoint is "
                "broken (a widen/reshape rides between rounds)"
            )))
    return out


def check_paged_roundtrip(rec_a, rec_b) -> list:
    """The two host-boundary programs must be aval-inverses: each one's
    out avals equal the other's example-arg avals positionally (both
    sides are (state, paged) pytrees of the same classes, so flatten
    order lines up by construction)."""
    def arg_avals(rec):
        return [(tuple(l.shape), str(l.dtype))
                for l in jax.tree.leaves(rec["args"])]

    def out_avals(rec):
        return [(tuple(a.shape), str(a.dtype))
                for a in trace_entry(rec).out_avals]

    out = []
    for src, dst in ((rec_a, rec_b), (rec_b, rec_a)):
        name = f"{src['name']}->{dst['name']}"
        got, want = out_avals(src), arg_avals(dst)
        if len(got) != len(want):
            out.append(Finding(name, "roundtrip", (
                f"{src['name']} returns {len(got)} leaves but "
                f"{dst['name']} consumes {len(want)} — the paged "
                "roundtrip no longer closes"
            )))
            continue
        for idx, (g, w) in enumerate(zip(got, want)):
            if g != w:
                out.append(Finding(name, "roundtrip", (
                    f"leaf {idx}: {src['name']} emits {g[1]}{list(g[0])} "
                    f"but {dst['name']} expects {w[1]}{list(w[0])} — a "
                    "reshape/upcast crept into the paged window cycle"
                )))
    return out


# --------------------------------------------------------------------------
# donation (lowered-HLO level)


def lower_text_and_warnings(rec) -> tuple[str, list]:
    """Lower the record's jit twin for its example args; returns the
    StableHLO text and any 'donated buffers were not usable' warnings
    jax emitted during lowering (each one is a donated leaf that lost
    its alias)."""
    jit = rec["jit"]
    kwargs = {**rec.get("static", {}), **rec.get("kwargs", {})}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = jit.lower(*rec["args"], **kwargs)
    text = lowered.as_text()
    dropped = [
        str(w.message)
        for w in caught
        if "donated buffers were not usable" in str(w.message).lower()
    ]
    return text, dropped


def donated_leaf_count(rec) -> int:
    """Leaves of the donated portion of the example args: positional
    donate_argnums (0, 1) = (state, fab) plus the donated plane kwargs
    that are not None."""
    donated = [rec["args"][i] for i in rec.get("donate_argnums", ())]
    for k in rec.get("donate_argnames", ()):
        val = rec.get("kwargs", {}).get(k)
        if val is not None:
            donated.append(val)
    return len(jax.tree.leaves(donated))


def check_donation(name, rec, lowered=None) -> list:
    """Donating twin: every donated carry leaf aliases an output (count
    `tf.aliasing_output`/`jax.buffer_donor` markers, catch jax's
    unusable-donation warning). Copying twin: aliases nothing.
    ``lowered`` lets the caller share one (text, dropped) lowering with
    the escape check."""
    text, dropped = lowered if lowered is not None \
        else lower_text_and_warnings(rec)
    aliased = text.count("tf.aliasing_output") + text.count("jax.buffer_donor")
    out = []
    if rec["donate"]:
        expected = donated_leaf_count(rec)
        if dropped:
            out.append(Finding(name, "donation", (
                f"{len(dropped)} donated leaf group(s) lost their alias "
                f"(silent HBM doubling): {dropped[0]}"
            )))
        if aliased < expected:
            out.append(Finding(name, "donation", (
                f"lowering aliases {aliased} buffers but the donated carry "
                f"has {expected} leaves — some donated leaf is not updated "
                "in place"
            )))
    else:
        if aliased:
            out.append(Finding(name, "donation", (
                f"copying twin aliases {aliased} buffers — stale host "
                "references to the pre-dispatch carry would read garbage"
            )))
    return out


# --------------------------------------------------------------------------
# donation escape (per-leaf alias accounting in the lowered signature)

_MAIN_ARG_RE = re.compile(r"%arg(\d+):")


def _main_arg_attrs(text: str) -> dict | None:
    """{flat arg position: signature span text} for the lowered
    program's public @main signature; None when the signature can't be
    found. Each span runs from this ``%argN:`` to the next — attr dicts
    can nest braces inside quoted strings (mhlo.sharding does), so
    span-slicing beats brace-matching."""
    m = re.search(r"func\.func public @main\((.*?)\)\s*->", text, re.S)
    if m is None:
        return None
    sig = m.group(1)
    marks = list(_MAIN_ARG_RE.finditer(sig))
    out = {}
    for i, am in enumerate(marks):
        end = marks[i + 1].start() if i + 1 < len(marks) else len(sig)
        out[int(am.group(1))] = sig[am.start():end]
    return out


def flat_arg_names(rec) -> tuple[list, set]:
    """(per-flat-leaf human names, donated flat positions) for a record's
    example arguments, in jax's flatten order: positional args in order,
    then kwargs sorted by key (how pjit flattens (args, kwargs))."""
    try:
        params = list(inspect.signature(rec["fn"]).parameters)
    except (TypeError, ValueError):  # pragma: no cover - exotic callables
        params = []
    names, donated = [], set()
    pos = 0
    for i, a in enumerate(rec["args"]):
        prefix = params[i] if i < len(params) else f"arg{i}"
        paths = jax.tree_util.tree_flatten_with_path(a)[0]
        for path, _ in paths:
            names.append(prefix + jax.tree_util.keystr(path))
        if i in rec.get("donate_argnums", ()):
            donated.update(range(pos, pos + len(paths)))
        pos += len(paths)
    kw = rec.get("kwargs", {})
    for k in sorted(kw):
        paths = jax.tree_util.tree_flatten_with_path(kw[k])[0]
        for path, _ in paths:
            names.append(k + jax.tree_util.keystr(path))
        if k in rec.get("donate_argnames", ()):
            donated.update(range(pos, pos + len(paths)))
        pos += len(paths)
    return names, donated


def check_donation_escape(name, rec, text: str | None = None) -> list:
    """Per-leaf donation escape analysis: every donated flat argument of
    the lowered program must carry an input-output alias attribute. A
    leaf without one ESCAPED donation — its buffer is both donated (the
    host must not read it after dispatch) and not reused (HBM doubles),
    the worst of both. Names the leaf via the record's example pytrees
    so the finding points at a column, not a position."""
    if text is None:
        text, _ = lower_text_and_warnings(rec)
    names, donated = flat_arg_names(rec)
    if not donated:
        return []
    attrs = _main_arg_attrs(text)
    if attrs is None:  # pragma: no cover - lowering layout drift
        return [Finding(name, "escape", (
            "could not locate the public @main signature in the lowered "
            "program — escape analysis can't run (lowering layout drift)"
        ))]

    def aliased(a: str) -> bool:
        return "tf.aliasing_output" in a or "jax.buffer_donor" in a

    if len(attrs) != len(names):
        # the lowering pruned unused args: flat positions shifted, so
        # degrade to count-level accounting rather than misname leaves
        n_aliased = sum(1 for a in attrs.values() if aliased(a))
        missing = len(donated) - n_aliased
        if missing > 0:
            return [Finding(name, "escape", (
                f"{missing} donated leaf/leaves have no input-output "
                "alias in the lowered program (argument pruning hides "
                "which) — donated buffers escape"
            ))]
        return []
    out = []
    for i in sorted(donated):
        if not aliased(attrs.get(i, "")):
            leaf = names[i] if i < len(names) else f"flat arg {i}"
            out.append(Finding(name, "escape", (
                f"donated leaf '{leaf}' (flat arg {i}) has no "
                "input-output alias in the lowered program — the donated "
                "buffer escapes: HBM doubles and any host view of it "
                "dangles after dispatch"
            )))
    return out


# --------------------------------------------------------------------------
# one record end-to-end


def audit_record(rec, *, expect_on=None, diet: bool = False) -> list:
    """Run every applicable static check on one program record; returns
    the finding list (empty = clean). Purely static: make_jaxpr +
    jit.lower only, nothing executes."""
    name = rec["name"]
    checks = rec.get("checks")
    jaxpr, deltas = traced_counter_deltas(rec)
    out = []

    def want(c):
        return checks is None or c in checks

    if want("elision") and expect_on:
        out += check_elision(name, deltas, expect_on)
    if want("dtype") and diet:
        # dtype_carry overrides the default (state, fab) pair when the
        # program's in-flight storage avals legitimately differ from the
        # between-dispatch carry (the paged profile: log columns ride the
        # scan at the paged-in full-window shape)
        carry = rec.get("dtype_carry") or [rec["args"][0], rec["args"][1]]
        out += check_dtype_discipline(name, jaxpr, carry)
    if want("capture"):
        out += check_constant_capture(name, jaxpr)
    if want("hygiene"):
        out += check_host_hygiene(name, jaxpr)
    if want("carry"):
        out += check_carry_stability(name, jaxpr, rec)
    if rec.get("jit") is not None and (want("donation") or want("escape")):
        lowered = lower_text_and_warnings(rec)
        if want("donation"):
            out += check_donation(name, rec, lowered=lowered)
        if want("escape") and rec.get("donate"):
            out += check_donation_escape(name, rec, text=lowered[0])
    return out


def audit_entries(pairs) -> tuple[list, list]:
    """Audit every (entry, record) pair plus the cross-record proofs
    (the paged roundtrip pairing records declare via ``roundtrip``).
    Returns (findings, per-entry report rows) — the shared driver for
    ``python -m raft_tpu.analysis`` and the all-green matrix test."""
    findings, rows = [], []
    recs = {rec["name"]: rec for _, rec in pairs}
    for entry, rec in pairs:
        fs = audit_record(rec, expect_on=entry.expect_on, diet=entry.diet)
        findings += fs
        rows.append({
            "name": entry.name,
            "profile": entry.profile,
            "compile_budget": entry.compile_budget,
            "findings": len(fs),
        })
    seen = set()
    for _, rec in pairs:
        peer = rec.get("roundtrip")
        if not peer or peer not in recs:
            continue
        key = frozenset((rec["name"], peer))
        if key in seen:
            continue
        seen.add(key)
        findings += check_paged_roundtrip(rec, recs[peer])
    return findings, rows
