"""Static jaxpr/HLO invariant checks over the registry's entry points.

Every check here runs WITHOUT executing a round: programs are traced with
`jax.make_jaxpr` and lowered with `jit.lower(...)`, never called. The
checks encode the compile-time contracts the rest of the repo asserts
ad-hoc in whichever test first needed them (see ISSUE/README):

- **elision** — with a plane's env knob off, zero primitives attributable
  to that plane anywhere in the program. Proven via the shared trace-time
  CallCounters (raft_tpu/testing/counters.py): the plane's device fn bumps
  its counter when TRACED, so a flat counter across the `make_jaxpr` of an
  entry point means the plane contributed nothing to the jaxpr. A plane
  that is ON must bump (positive sanity — a counter that never moves
  can't prove elision).
- **donation** — a donating twin's lowering must carry an input-output
  alias for every donated carry leaf; a donated leaf that LOST its alias
  (jax lowers it with a "donated buffers were not usable" warning and no
  `tf.aliasing_output` attribute) is a silent HBM doubling. The copying
  twin must alias nothing.
- **dtype discipline** — under RAFT_TPU_DIET=1 the packed carry columns
  (uint16 indexes/terms, int8 ids, int16 sizes, uint8/16/32 bitsets) must
  ride the scan carry / pallas operands in their packed dtypes. The
  in-body widen/compute/narrow cycle is by design; what must never happen
  is a packed column riding the BETWEEN-rounds carry widened to int32 —
  so the check asserts every narrow leaf of the actual carry appears
  among the program's scan-carry/kernel-operand avals.
- **constant capture** — no jaxpr consts feeding a `pallas_call` (the
  jax 0.4.37 lifted-literal hazard from PR 4: enum scalars and array
  literals become constvars that Mosaic rejects or bakes into the
  kernel), and no large (>16 KiB) const anywhere in the program (a
  captured table silently re-uploads per executable).
- **host-boundary hygiene** — no host callbacks/infeed/outfeed inside a
  round-dispatch program: the round must be pure device code; a stray
  `debug_callback`/`pure_callback` forces a host sync per dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
import warnings

import jax

from raft_tpu.testing import counters as ctr

# dtypes the diet-v2 pack boundary may produce; anything in the carry with
# one of these is a "packed column" the program must preserve
NARROW_DTYPES = ("uint8", "uint16", "uint32", "int8", "int16")

# one const bigger than this anywhere in a program is a capture bug (the
# engine passes all real data as arguments; consts should be iota/scalars)
MAX_CONST_BYTES = 16 * 1024

# primitives that cross the host boundary inside a device program
_HOST_PRIMS = ("infeed", "outfeed")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One invariant violation. `entry` names the manifest entry point,
    `check` the auditor pass, `detail` the human-readable evidence."""

    entry: str
    check: str
    detail: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


# --------------------------------------------------------------------------
# program tracing


def trace_entry(rec) -> "jax.core.ClosedJaxpr":
    """Trace a registry program record to its closed jaxpr without
    executing it. Static kwargs close over the fn; array args/kwargs are
    passed as tracer arguments so real data never becomes a jaxpr const
    (which would defeat the constant-capture check)."""
    fn = functools.partial(rec["fn"], **rec.get("static", {}))
    return jax.make_jaxpr(fn)(*rec["args"], **rec.get("kwargs", {}))


def traced_counter_deltas(rec) -> tuple["jax.core.ClosedJaxpr", dict]:
    """(closed_jaxpr, {plane: trace-time counter delta}) for one record."""
    before = ctr.snapshot()
    jaxpr = trace_entry(rec)
    after = ctr.snapshot()
    return jaxpr, {k: after[k] - before.get(k, 0) for k in after}


# --------------------------------------------------------------------------
# jaxpr walking


def iter_jaxprs(jaxpr):
    """Yield (jaxpr, constvar_set) for the top jaxpr and every sub-jaxpr
    reachable through eqn params (scan/cond/pjit/pallas bodies)."""
    seen = set()

    def walk(jx):
        if id(jx) in seen:
            return
        seen.add(id(jx))
        yield jx, set(jx.constvars)
        for eqn in jx.eqns:
            for sub in _sub_jaxprs(eqn):
                yield from walk(sub)

    yield from walk(jaxpr.jaxpr if hasattr(jaxpr, "jaxpr") else jaxpr)


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        for item in v if isinstance(v, (tuple, list)) else (v,):
            if hasattr(item, "jaxpr") and hasattr(item.jaxpr, "eqns"):
                yield item.jaxpr  # ClosedJaxpr
            elif hasattr(item, "eqns"):
                yield item  # raw Jaxpr


def iter_eqns(jaxpr):
    for jx, _ in iter_jaxprs(jaxpr):
        yield from jx.eqns


def _aval_key(aval) -> tuple:
    return (tuple(aval.shape), str(aval.dtype))


def storage_avals(jaxpr) -> set:
    """The program's "storage" avals: scan-carry avals (what HBM holds
    between rounds) plus pallas_call operand avals (what the kernel is
    fed). These are the positions where the diet's packed dtypes must
    survive."""
    out = set()
    for eqn in iter_eqns(jaxpr):
        name = eqn.primitive.name
        if name == "scan":
            nc = eqn.params.get("num_consts", 0)
            ncar = eqn.params.get("num_carry", 0)
            body = eqn.params.get("jaxpr")
            invars = body.jaxpr.invars if hasattr(body, "jaxpr") else body.invars
            for v in invars[nc : nc + ncar]:
                out.add(_aval_key(v.aval))
        elif name == "pallas_call":
            for v in eqn.invars:
                if hasattr(v, "aval"):
                    out.add(_aval_key(v.aval))
    return out


def narrow_carry_avals(tree) -> set:
    """The (shape, dtype) set of every packed-dtype leaf in an actual
    carry pytree — what the program's storage avals must cover."""
    out = set()
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "dtype") and str(leaf.dtype) in NARROW_DTYPES:
            out.add((tuple(leaf.shape), str(leaf.dtype)))
    return out


# --------------------------------------------------------------------------
# checks (each returns a list of Findings)


def check_elision(name, deltas, expect_on: dict) -> list:
    """expect_on: {plane: bool} — planes expected IN the program must have
    bumped their trace-time counter during the trace; planes expected OFF
    must not have."""
    out = []
    for plane, on in expect_on.items():
        d = deltas.get(plane, 0)
        if on and d <= 0:
            out.append(Finding(name, "elision", (
                f"plane '{plane}' is enabled but its device fn was never "
                "traced into the program (counter flat) — the plane "
                "silently dropped out"
            )))
        if not on and d > 0:
            out.append(Finding(name, "elision", (
                f"plane '{plane}' is disabled but its device fn was traced "
                f"{d}x into the program — elision is broken, the knob no "
                "longer compiles the plane out"
            )))
    return out


def check_dtype_discipline(name, jaxpr, carry) -> list:
    """Every packed (narrow-dtype) leaf of the real carry must appear among
    the program's scan-carry / pallas-operand avals with its packed shape
    and dtype. A missing one means some path widened it (usually to int32)
    for the ride between rounds — the silent byte-diet regression."""
    have = storage_avals(jaxpr)
    if not have:
        return []  # no scan/kernel in this program — nothing rides a carry
    out = []
    for shape, dtype in sorted(narrow_carry_avals(carry)):
        if (shape, dtype) not in have:
            out.append(Finding(name, "dtype", (
                f"packed carry column {dtype}{list(shape)} does not appear "
                "in any scan carry / kernel operand — a cast widened it "
                "between rounds (diet regression)"
            )))
    return out


def check_constant_capture(name, jaxpr) -> list:
    out = []
    for jx, constvars in iter_jaxprs(jaxpr):
        for eqn in jx.eqns:
            if eqn.primitive.name != "pallas_call":
                continue
            for v in eqn.invars:
                if not hasattr(v, "aval"):
                    continue
                if v in constvars:
                    out.append(Finding(name, "capture", (
                        f"pallas_call operand {v.aval.str_short()} is a "
                        "lifted jaxpr const (captured closure/enum "
                        "constant) — pass it as an argument or register "
                        "the literal (types.register_literal_enums)"
                    )))
    top = jaxpr if hasattr(jaxpr, "consts") else None
    if top is not None:
        for c in top.consts:
            nbytes = getattr(c, "nbytes", 0)
            if nbytes > MAX_CONST_BYTES:
                out.append(Finding(name, "capture", (
                    f"program captures a {nbytes}-byte const "
                    f"{getattr(c, 'dtype', '?')}{list(getattr(c, 'shape', ()))}"
                    " — real data must ride as an argument, not a closure"
                )))
    return out


def check_host_hygiene(name, jaxpr) -> list:
    out = []
    for eqn in iter_eqns(jaxpr):
        pname = eqn.primitive.name
        if "callback" in pname or pname in _HOST_PRIMS:
            out.append(Finding(name, "hygiene", (
                f"primitive '{pname}' inside the round-dispatch program — "
                "a host round-trip per dispatch; move it to the host plane "
                "or behind a stream drain"
            )))
    return out


# --------------------------------------------------------------------------
# donation (lowered-HLO level)


def lower_text_and_warnings(rec) -> tuple[str, list]:
    """Lower the record's jit twin for its example args; returns the
    StableHLO text and any 'donated buffers were not usable' warnings
    jax emitted during lowering (each one is a donated leaf that lost
    its alias)."""
    jit = rec["jit"]
    kwargs = {**rec.get("static", {}), **rec.get("kwargs", {})}
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = jit.lower(*rec["args"], **kwargs)
    text = lowered.as_text()
    dropped = [
        str(w.message)
        for w in caught
        if "donated buffers were not usable" in str(w.message).lower()
    ]
    return text, dropped


def donated_leaf_count(rec) -> int:
    """Leaves of the donated portion of the example args: positional
    donate_argnums (0, 1) = (state, fab) plus the donated plane kwargs
    that are not None."""
    donated = [rec["args"][i] for i in rec.get("donate_argnums", ())]
    for k in rec.get("donate_argnames", ()):
        val = rec.get("kwargs", {}).get(k)
        if val is not None:
            donated.append(val)
    return len(jax.tree.leaves(donated))


def check_donation(name, rec) -> list:
    """Donating twin: every donated carry leaf aliases an output (count
    `tf.aliasing_output`/`jax.buffer_donor` markers, catch jax's
    unusable-donation warning). Copying twin: aliases nothing."""
    text, dropped = lower_text_and_warnings(rec)
    aliased = text.count("tf.aliasing_output") + text.count("jax.buffer_donor")
    out = []
    if rec["donate"]:
        expected = donated_leaf_count(rec)
        if dropped:
            out.append(Finding(name, "donation", (
                f"{len(dropped)} donated leaf group(s) lost their alias "
                f"(silent HBM doubling): {dropped[0]}"
            )))
        if aliased < expected:
            out.append(Finding(name, "donation", (
                f"lowering aliases {aliased} buffers but the donated carry "
                f"has {expected} leaves — some donated leaf is not updated "
                "in place"
            )))
    else:
        if aliased:
            out.append(Finding(name, "donation", (
                f"copying twin aliases {aliased} buffers — stale host "
                "references to the pre-dispatch carry would read garbage"
            )))
    return out


# --------------------------------------------------------------------------
# one record end-to-end


def audit_record(rec, *, expect_on=None, diet: bool = False) -> list:
    """Run every applicable static check on one program record; returns
    the finding list (empty = clean). Purely static: make_jaxpr +
    jit.lower only, nothing executes."""
    name = rec["name"]
    checks = rec.get("checks")
    jaxpr, deltas = traced_counter_deltas(rec)
    out = []

    def want(c):
        return checks is None or c in checks

    if want("elision") and expect_on:
        out += check_elision(name, deltas, expect_on)
    if want("dtype") and diet:
        carry = [rec["args"][0], rec["args"][1]]
        out += check_dtype_discipline(name, jaxpr, carry)
    if want("capture"):
        out += check_constant_capture(name, jaxpr)
    if want("hygiene"):
        out += check_host_hygiene(name, jaxpr)
    if want("donation") and rec.get("jit") is not None:
        out += check_donation(name, rec)
    return out
