"""``python -m raft_tpu.analysis`` — the repo's static analysis gate.

Runs, in order:

1. the repo lint (AST only, no jax),
2. the jaxpr/HLO audit over every registry entry point (capture,
   hygiene, donation, carry stability, donation escape, paged
   roundtrips),
3. the recompile sentinel (unless ``--no-sentinel``),
4. the compiled-program resource ledger (``--ledger``): AOT-compile
   every entry, extract cost/memory analysis, diff the per-lane
   metrics against the checked-in LEDGER.json budgets.

The ledger deliberately runs AFTER the sentinel: its ``lower().
compile()`` calls hit the same process-wide jax caches, and running
them first would make the sentinel's compile counters meaningless.

Writes the combined report to ANALYSIS.json (``--json`` to move it),
prints a one-line-per-finding summary, and exits non-zero on any
finding. ``--lint-only`` stops after step 1 for the fastest gate.
``--update-ledger`` re-baselines LEDGER.json from the current build
instead of gating (implies ``--ledger``); the human-readable diff of
the last ledger run lands in LEDGER_DIFF.txt next to the report.

Env pinning happens BEFORE jax is imported: unless the caller already
chose, the gate runs on the CPU platform with 8 host devices so the
sharded stepper entry is auditable anywhere (the same arrangement
runtests.sh uses).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _pin_env():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m raft_tpu.analysis")
    ap.add_argument("--json", default="ANALYSIS.json",
                    help="report path (default: ANALYSIS.json in cwd)")
    ap.add_argument("--lint-only", action="store_true",
                    help="run only the AST lint (no jax import)")
    ap.add_argument("--no-sentinel", action="store_true",
                    help="skip the recompile sentinel (audit + lint only)")
    ap.add_argument("--ledger", action="store_true",
                    help="AOT-compile every entry and gate the per-lane "
                         "cost/memory metrics against LEDGER.json")
    ap.add_argument("--update-ledger", action="store_true",
                    help="re-baseline LEDGER.json from the current build "
                         "instead of gating (implies --ledger)")
    args = ap.parse_args(argv)
    if args.update_ledger:
        args.ledger = True

    _pin_env()
    findings = []
    report = {"findings": [], "lint": None, "entries": None,
              "recompile": None, "ledger": None}

    from raft_tpu.analysis.lint import run_lint

    lint_findings, lint_report = run_lint()
    findings += lint_findings
    report["lint"] = lint_report

    if not args.lint_only:
        from raft_tpu.analysis import jaxpr_audit
        from raft_tpu.analysis.registry import build_records

        pairs = build_records()
        audit_findings, entries = jaxpr_audit.audit_entries(pairs)
        findings += audit_findings
        report["entries"] = entries

        if not args.no_sentinel:
            from raft_tpu.analysis.recompile import run_sentinel

            sentinel_findings, sentinel_report = run_sentinel()
            findings += sentinel_findings
            report["recompile"] = sentinel_report

        if args.ledger:
            from raft_tpu.analysis import ledger

            ledger_findings, ledger_report = ledger.run_ledger(
                pairs, update=args.update_ledger
            )
            findings += ledger_findings
            report["ledger"] = ledger_report
            diff_path = os.path.join(
                os.path.dirname(os.path.abspath(args.json)),
                "LEDGER_DIFF.txt",
            )
            with open(diff_path, "w") as fh:
                fh.write(ledger_report.get("diff") or "(no diff)\n")

    report["findings"] = [f.as_dict() for f in findings]
    report["ok"] = not findings
    with open(args.json, "w") as fh:
        json.dump(report, fh, indent=2, sort_keys=True)
        fh.write("\n")

    n_entries = len(report["entries"] or [])
    print(f"raft_tpu.analysis: {len(findings)} finding(s) "
          f"across {n_entries} entry point(s); report -> {args.json}")
    for f in findings:
        print(f"  [{f.check}] {f.entry}: {f.detail}")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
