"""Egress extraction: cross-host outbound fabric cells -> compact bundle.

One jitted kernel per host (ops/ready_mask.py style): a [4 * N * V]
presence mask (per-channel kind != MSG_NONE, restricted to the host's
static xedge cells) is cumsum-compacted into a dense index prefix, the
message fields are gathered through that prefix into `cap`-sized columns,
and the exported cells' kinds are cleared to MSG_NONE in the returned
carry — so ghost lanes never receive locally and the wire is the ONLY
path a cross-host message can take. Device->host transfer is O(active):
`cap` columns regardless of fleet size, trimmed to the actual count on
the host.

The gathered columns are the superset of all four channel schemas
(rep/hb/vote/vresp; placement.CHANNELS order); fields a channel lacks
gather as 0 and are never scattered back on the inject side, so the
gather/scatter pair is symmetric per channel. Entry columns ([cap, E])
only exist on the rep channel and use a second fill-gather.

Clearing preserves the stored carry dtypes (slim int8 kinds under
FABRIC_SLIM), while reads go through unpack_fabric + fat_fabric so the
same kernel serves diet and non-diet carries.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.fabric import fabric_cap
from raft_tpu.fabric.placement import CHANNELS, decode_positions
from raft_tpu.ops import fused as fz
from raft_tpu.ops.ready_mask import compact_mask
from raft_tpu.types import MessageType as MT

I32 = jnp.int32

# Superset scalar schema, one [cap] i32 column per name on the wire; the
# per-channel subsets below drive both the gather here and the scatter in
# inject.py (a channel's dataclass fields are exactly its subset).
SCALAR_FIELDS = (
    "kind",
    "term",
    "index",
    "log_term",
    "commit",
    "reject",
    "reject_hint",
    "n_ents",
    "context",
    "snap_index",
    "snap_term",
)
ENT_FIELDS = ("ent_term", "ent_type", "ent_bytes")  # rep only, [cap, E]

BUNDLE_FIELDS = SCALAR_FIELDS + ENT_FIELDS


@dataclasses.dataclass
class Bundle:
    """Host-side decoded extract output: k messages in columnar form.
    chan indexes placement.CHANNELS; cell = src_lane * V + dst_slot in
    the CANONICAL (global) lane space, identical on every host.

    `round` is the EMIT round tag that rides the wire header: the
    absolute round whose post-round carry the messages were extracted
    from, re-stamped to the release round when a chaos wire_delay defers
    the bundle (merge_bundles). The lockstep receiver injects before
    round+1; a bounded-skew receiver keys its staging map by (peer,
    round) and injects before round+D+1 (driver.py)."""

    chan: np.ndarray  # [k] u8
    cell: np.ndarray  # [k] u32
    cols: dict  # {name: [k] i32} scalars + {ent_*: [k, E] i32}
    round: int = -1

    @property
    def count(self) -> int:
        return int(self.chan.shape[0])

    @classmethod
    def empty(cls, n_ents: int, rnd: int = -1) -> "Bundle":
        cols = {f: np.zeros((0,), np.int32) for f in SCALAR_FIELDS}
        cols.update({f: np.zeros((0, n_ents), np.int32) for f in ENT_FIELDS})
        return cls(np.zeros((0,), np.uint8), np.zeros((0,), np.uint32), cols, rnd)


def merge_bundles(bundles, n_ents: int, rnd: int = -1) -> Bundle:
    """Concatenate bundles (the wire-delay release path merges deferred
    bundles into the current frame). Distinct (chan, cell) sets by
    construction — each cell is extracted by exactly one owner host."""
    bundles = [b for b in bundles if b is not None and b.count]
    if not bundles:
        return Bundle.empty(n_ents, rnd)
    cols = {
        f: np.concatenate([b.cols[f] for b in bundles]) for f in BUNDLE_FIELDS
    }
    return Bundle(
        np.concatenate([b.chan for b in bundles]),
        np.concatenate([b.cell for b in bundles]),
        cols,
        rnd,
    )


def extract_bundle(fab, xedge, own, *, cap: int):
    """Pull (and clear) the cross-host outbound cells of one round carry.

    fab    the post-round Fabric carry (slim and/or diet-packed dtypes)
    xedge  [N, V] bool static outbound cross-host cells (placement.xedge)
    own    [N] bool static owned-lane mask (for the msgs_total count)
    cap    static bundle capacity; count > cap is detected on the host

    Returns (cleared_fab, out) where out carries pos [cap] (flat position
    chan * N*V + cell, tail = sentinel 4*N*V), count, total (ALL non-NONE
    owned-src messages this round, local + cross — the bench's
    cross-vs-total denominator), and the gathered superset columns.
    """
    wide = fz.fat_fabric(fz.unpack_fabric(fab))
    n, v = wide.hb.kind.shape
    nv = n * v
    chans = tuple(getattr(wide, c) for c in CHANNELS)

    pres = [((c.kind != MT.MSG_NONE) & xedge).reshape(nv) for c in chans]
    active, count = compact_mask(jnp.concatenate(pres))
    idx = active[:cap]  # [cap], tail = 4*nv sentinel -> fill-gathers 0

    total = sum(
        jnp.sum(((c.kind != MT.MSG_NONE) & own[:, None]).astype(I32))
        for c in chans
    )

    def stack(field):
        cols = []
        for c in chans:
            x = getattr(c, field, None)
            cols.append(
                x.reshape(nv).astype(I32)
                if x is not None
                else jnp.zeros((nv,), I32)
            )
        return jnp.concatenate(cols)

    out = {
        f: jnp.take(stack(f), idx, mode="fill", fill_value=0)
        for f in SCALAR_FIELDS
    }
    # rep-only entry columns: gather rows of [nv, E] by cell, but only for
    # positions in the rep channel block (chan 0 <=> pos < nv)
    ent_idx = jnp.where(idx < nv, idx % nv, nv)
    for f in ENT_FIELDS:
        x = getattr(wide.rep, f)
        out[f] = jnp.take(
            x.reshape(nv, -1).astype(I32),
            ent_idx,
            axis=0,
            mode="fill",
            fill_value=0,
        )
    out["pos"] = idx
    out["count"] = count
    out["total"] = total

    # clear every xedge cell (occupied or not: empties are already NONE)
    # preserving the stored carry dtypes so slim/diet layouts round-trip
    cleared = {}
    for name in CHANNELS:
        c = getattr(fab, name)
        none = jnp.asarray(int(MT.MSG_NONE), c.kind.dtype)
        cleared[name] = dataclasses.replace(
            c, kind=jnp.where(xedge, none, c.kind)
        )
    return dataclasses.replace(fab, **cleared), out


_extract_jit = jax.jit(extract_bundle, static_argnames=("cap",))


class FabricExtractor:
    """Per-host extract endpoint: owns the static masks, the capacity, and
    the device->host trim. Hosts with no cross edges skip the kernel
    entirely (pure-local placements never build a fabric program)."""

    def __init__(self, placement, host: int, cap: int | None = None):
        self.placement = placement
        self.host = int(host)
        self.n_cross = placement.n_cross_cells(host)
        # lossless default: one message per channel per cross cell per
        # round is the most one round can emit (the outbox is rebuilt
        # from empty each round)
        self.cap = int(
            cap if cap is not None else (fabric_cap() or len(CHANNELS) * self.n_cross)
        )
        self._xedge = jnp.asarray(placement.xedge(host))
        self._own = jnp.asarray(placement.own_mask(host))

    def __call__(self, fab, rnd: int = -1):
        """-> (cleared_fab, Bundle, total_msgs). Bundle is None when this
        host has no cross edges (nothing to clear either)."""
        if self.n_cross == 0:
            return fab, None, 0
        cleared, out = _extract_jit(fab, self._xedge, self._own, cap=self.cap)
        count = int(out["count"])
        if count > self.cap:
            raise RuntimeError(
                f"fabric extract overflow: {count} cross-host messages in one "
                f"round > cap {self.cap} (host {self.host}); raise "
                f"RAFT_TPU_FABRIC_CAP"
            )
        pos = np.asarray(out["pos"])[:count]
        chan, cell, _src, _dst = decode_positions(
            pos, self.placement.n_lanes, self.placement.n_voters
        )
        cols = {
            f: np.asarray(out[f])[:count].astype(np.int32)
            for f in BUNDLE_FIELDS
        }
        return cleared, Bundle(chan, cell, cols, rnd), int(out["total"])


def split_bundle(bundle: Bundle, placement, n_ents: int) -> dict:
    """Partition one host's extract bundle by destination host (the owner
    of each message's dst lane) -> {host: Bundle}."""
    out = {}
    if bundle is None or bundle.count == 0:
        return out
    dst = placement.dst_host_of_cells(bundle.cell)
    for h in np.unique(dst):
        sel = dst == h
        cols = {f: bundle.cols[f][sel] for f in BUNDLE_FIELDS}
        out[int(h)] = Bundle(bundle.chan[sel], bundle.cell[sel], cols, bundle.round)
    return out
