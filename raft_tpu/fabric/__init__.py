"""Cross-host fabric: N independent engine processes as one logical fleet.

The mesh driver (parallel/mesh.py) tops out at one process; this package
is ROADMAP item 4's milestone 1 — federate several engine PROCESSES over
a framed wire so groups placed host-local never touch the network and
only cross-host quorums pay it (the bridge-framing path, scaled from the
per-message RawNode bridge to the fused engine's channel fabric):

  placement.py  global (group, voter) id space partitioned into per-host
                shards; spanning groups get their cross-host fabric edges
                marked at construction (static [N, V] masks)
  extract.py    jitted O(active) kernel (ops/ready_mask.py style) pulling
                only the cross-host outbound cells from the round carry
                into a compact host bundle, clearing them so ghost lanes
                never receive locally
  wire.py       length-prefixed frames over sockets/pipes — byte-exact
                raftpb via runtime/codec.py's columnar frame codec, or a
                raw columnar encoding with an EQuARX-style sub-int16 diet
                (RAFT_TPU_FABRIC_DIET)
  inject.py     decoded frames land as fabric ops at the destination
                host's next round boundary, exactly like local ops
  driver.py     round-synchronous lockstep coordinator (milestone 1) +
                the multiprocess launcher tests/benches fork workers with

Everything is gated behind RAFT_TPU_FABRIC (default OFF, read through
config accessors at construction): with the knob off no fabric object can
be built and no fabric jit exists — the same full-elision contract as the
metrics/chaos/trace planes.
"""

from __future__ import annotations

from raft_tpu import config


def fabric_enabled() -> bool:
    """RAFT_TPU_FABRIC (default OFF), read at construction like the other
    planes: FabricHost/LockstepFabric refuse to build when off, so the
    extract/inject jits never exist in a fabric-off process."""
    return config.env_flag("RAFT_TPU_FABRIC", default=False)


def fabric_cap() -> int:
    """RAFT_TPU_FABRIC_CAP: static extract/inject bundle capacity override
    (messages per round per host). 0 (default) derives the lossless bound
    4 x cross-host cells — one message per channel per edge per round is
    the most one round can emit, so the default can never drop."""
    return config.env_int("RAFT_TPU_FABRIC_CAP", default=0)


def fabric_skew() -> int:
    """RAFT_TPU_FABRIC_SKEW: bounded-skew pipeline depth D (default 0 =
    lockstep). The wire contract becomes a fixed D-round latency — a frame
    emitted at round r is injected before the receiver's round r+D+1 — so
    each host may run up to D rounds ahead of its slowest peer and socket
    I/O overlaps compute. Deterministic by construction: the skewed fleet
    is bit-identical to a lockstep fleet running a uniform D-round
    chaos wire_delay on every fabric edge (driver.py's twin oracle)."""
    d = config.env_int("RAFT_TPU_FABRIC_SKEW", default=0)
    if d < 0:
        raise ValueError(f"RAFT_TPU_FABRIC_SKEW must be >= 0, got {d}")
    return d
