"""Global (group, voter) placement over hosts — the fabric's static map.

One logical fleet of `n_groups` x `n_voters` canonical lanes is
partitioned over `n_hosts` processes by an `owners [G, V]` table: host
`owners[g, j]` runs the real replica of member j of group g. Every host
still constructs the FULL monolithic geometry (same seed, same per-lane
PRNG and timeouts as the single-process cluster — that identity is what
the digest-parity oracle leans on); lanes owned elsewhere are ghosts in
the bridge sense (runtime/bridge.py): marked learners in their own view
so no tick can ever campaign them, stripped of inbound traffic by the
extract kernel, and therefore forever silent — free outbox space whose
cells carry the owner's outbound cross-host messages.

Everything here is host-side numpy computed once at construction; the
products are the STATIC masks the jitted extract/inject kernels close
over:

  own_mask(h)   [N]    lanes host h runs for real
  ghost_mask(h) [N]    lanes host h mirrors for geometry only
  xedge(h)      [N, V] outbound cross-host fabric cells: src lane owned
                       by h, dst slot's lane owned elsewhere
  in_cells(h)   [N, V] the inbound mirror (src ghost, dst owned) — the
                       inject kernel's landing sites

A group whose V members all land on one host never appears in any xedge
mask — host-local groups provably never touch the wire.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# Wire channel indexes: position of each Fabric channel in the extract
# bundle's flattened [4 * N * V] presence mask (and in every frame row).
# self_ never crosses the wire: it is the lane's message to itself.
CHANNELS = ("rep", "hb", "vote", "vresp")
N_CHANNELS = len(CHANNELS)


@dataclasses.dataclass(frozen=True)
class Placement:
    """Immutable fleet map; plain ints + one numpy table, so it pickles
    cleanly into spawned worker processes."""

    n_groups: int
    n_voters: int
    n_hosts: int
    owners: np.ndarray  # [G, V] int32 host id of each member

    # -- constructors ------------------------------------------------------

    @classmethod
    def contiguous(cls, n_groups: int, n_voters: int, n_hosts: int) -> "Placement":
        """Groups split into contiguous per-host runs, all members local:
        the all-local baseline (zero wire traffic)."""
        per = -(-n_groups // n_hosts)  # ceil
        own = np.repeat(
            np.minimum(np.arange(n_groups) // per, n_hosts - 1), n_voters
        )
        return cls(n_groups, n_voters, n_hosts, own.reshape(n_groups, n_voters).astype(np.int32))

    @classmethod
    def mostly_local(
        cls, n_groups: int, n_voters: int, n_hosts: int, spanning=()
    ) -> "Placement":
        """Contiguous placement, except each group in `spanning` donates
        its LAST voter slot to the next host — the canonical mostly-local
        fleet: most groups never touch the wire, the named ones run a
        cross-host quorum."""
        p = cls.contiguous(n_groups, n_voters, n_hosts)
        owners = p.owners.copy()
        for g in spanning:
            owners[int(g), n_voters - 1] = (owners[int(g), n_voters - 1] + 1) % n_hosts
        return cls(n_groups, n_voters, n_hosts, owners)

    # -- validation --------------------------------------------------------

    def __post_init__(self):
        owners = np.asarray(self.owners, dtype=np.int32)
        if owners.shape != (self.n_groups, self.n_voters):
            raise ValueError(
                f"owners must be [{self.n_groups}, {self.n_voters}], got {owners.shape}"
            )
        if owners.min(initial=0) < 0 or owners.max(initial=0) >= self.n_hosts:
            raise ValueError("owner host ids must be in [0, n_hosts)")
        object.__setattr__(self, "owners", owners)

    # -- lane-space views --------------------------------------------------

    @property
    def n_lanes(self) -> int:
        return self.n_groups * self.n_voters

    def owner_of_lane(self) -> np.ndarray:
        """[N] host id owning each canonical lane g*V + j."""
        return self.owners.reshape(-1)

    def own_mask(self, host: int) -> np.ndarray:
        """[N] bool: lanes host `host` runs for real."""
        return self.owner_of_lane() == int(host)

    def ghost_mask(self, host: int) -> np.ndarray:
        """[N] bool: lanes host `host` mirrors as silent ghosts."""
        return ~self.own_mask(host)

    def xedge(self, host: int) -> np.ndarray:
        """[N, V] bool outbound cross-host cells for `host`: fabric cell
        (lane, j) where `lane` is owned here and group-member j is owned
        elsewhere. Exactly the cells the extract kernel pulls and clears;
        all other cells (local traffic, ghost rows) stay on device."""
        own = self.own_mask(host)  # [N]
        g = self.n_groups
        v = self.n_voters
        # dst lane of cell (lane, j) is (lane // v) * v + j; owned-ness of
        # the dst therefore only depends on (group, j):
        dst_own = own.reshape(g, v)  # [G, V] member j of group g owned here
        return own[:, None] & ~np.repeat(dst_own, v, axis=0)

    def in_cells(self, host: int) -> np.ndarray:
        """[N, V] bool inbound cells for `host`: src lane ghost here, dst
        member owned here — where decoded frames scatter (bridge IMPORT:
        the message sits exactly where the remote sender's own outbox
        write would, so next round's route transpose delivers it)."""
        own = self.own_mask(host)
        g, v = self.n_groups, self.n_voters
        dst_own = own.reshape(g, v)
        return (~own[:, None]) & np.repeat(dst_own, v, axis=0)

    def n_cross_cells(self, host: int) -> int:
        return int(self.xedge(host).sum())

    def n_in_cells(self, host: int) -> int:
        return int(self.in_cells(host).sum())

    # -- group-space views -------------------------------------------------

    def hosts_of_group(self, g: int) -> tuple:
        return tuple(sorted(set(int(h) for h in self.owners[int(g)])))

    def spanning_groups(self) -> tuple:
        """Groups whose members live on more than one host — the only
        groups that ever pay the wire."""
        return tuple(
            g for g in range(self.n_groups) if len(self.hosts_of_group(g)) > 1
        )

    def local_groups(self, host: int) -> tuple:
        """Groups entirely owned by `host` (never on any xedge mask)."""
        return tuple(
            g
            for g in range(self.n_groups)
            if self.hosts_of_group(g) == (int(host),)
        )

    def peers(self, host: int) -> tuple:
        """Hosts that share at least one spanning group with `host` — the
        fabric edges the lockstep driver exchanges one frame per round
        over (in both directions, so an empty frame doubles as the round
        barrier)."""
        out = set()
        for g in self.spanning_groups():
            hs = self.hosts_of_group(g)
            if int(host) in hs:
                out |= set(hs)
        out.discard(int(host))
        return tuple(sorted(out))

    def shared_groups(self, a: int, b: int) -> tuple:
        """Spanning groups with members on BOTH hosts `a` and `b` — the
        groups whose traffic rides the (a, b) fabric edge. The skewed
        driver labels its backpressure wait-spans with this set so a slow
        peer is attributable to the quorums it stalls."""
        a, b = int(a), int(b)
        return tuple(
            g
            for g in self.spanning_groups()
            if a in self.hosts_of_group(g) and b in self.hosts_of_group(g)
        )

    def dst_host_of_cells(self, cell: np.ndarray) -> np.ndarray:
        """Destination host of flat fabric cells (cell = src_lane * V + j):
        the owner of the dst lane (src_lane // V) * V + j."""
        cell = np.asarray(cell, dtype=np.int64)
        v = self.n_voters
        src_lane = cell // v
        dst_lane = (src_lane // v) * v + (cell % v)
        return self.owner_of_lane()[dst_lane]


def decode_positions(pos: np.ndarray, n_lanes: int, n_voters: int):
    """Split flat extract-bundle positions (pos in [0, 4*N*V)) into
    (chan, cell, src_lane, dst_lane) columns."""
    pos = np.asarray(pos, dtype=np.int64)
    nv = int(n_lanes) * int(n_voters)
    chan = pos // nv
    cell = pos % nv
    src_lane = cell // n_voters
    dst_lane = (src_lane // n_voters) * n_voters + (cell % n_voters)
    return (
        chan.astype(np.uint8),
        cell.astype(np.uint32),
        src_lane.astype(np.int64),
        dst_lane.astype(np.int64),
    )
