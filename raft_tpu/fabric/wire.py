"""Wire layer: extract bundles <-> length-prefixed frames between hosts.

Frame = 20-byte header + payload. Two payload codecs, selectable via
RAFT_TPU_FABRIC_CODEC ("pb" | "np"; default auto = pb when the native
raftpb library loads, np otherwise):

  pb  byte-exact gogoproto raftpb via runtime/codec.py's columnar frame
      codec (one native call per frame) — the bridge.py convention
      exactly: global raft id of canonical lane L is L + 1, entry rows
      are (type, term, prev_index + 1 + k) with synthesized zero
      payloads of the carried sizes, MSG_SNAP rows carry
      (snap_index, snap_term) metadata + the group's member ids. A Go
      peer can split the frame and Unmarshal each message.

  np  raw little-endian columnar dump of the superset schema — the
      dependency-free path and the seam for the EQuARX-style diet:
      RAFT_TPU_FABRIC_DIET=1 narrows every field the byte-diet layer
      already bounds below int16 (uint16 terms/indexes/commits, int8
      kinds/types/counts, int16 entry sizes) on the wire, cutting frame
      bytes ~55% (gated in benches/fabric_ab.py). Requires
      RAFT_TPU_DIET=1 — without the diet's auto-rebase those bounds
      don't hold and construction refuses.

Both codecs are exact (quantization only narrows storage of already-
bounded values, never rounds), so the digest-parity oracle holds under
either. Persist-before-send: frames are encoded from the post-round
carry, after the fused round's synchronous persist has already advanced
`stabled` past every appended entry — by the time a frame exists, its
contents are stable locally (see driver.py).

Frame header round field = the EMIT round: the absolute round whose
post-round carry the payload was extracted from (a chaos wire_delay
re-emits deferred bundles under the release round, so the tag always
matches the frame's wire slot). Lockstep receivers inject it before
round r+1; a bounded-skew receiver (RAFT_TPU_FABRIC_SKEW=D) stages it
under (peer, emit_round) and injects before round r+D+1 — the fixed
D-round wire contract driver.py's twin oracle leans on.

Telemetry summary section (RAFT_TPU_FABRIC_DIET + np codec, skewed
fleets): FLAG_SUM frames carry an EQuARX-style quantized summary of the
sender's per-edge counter deltas (int8-style: 7-bit magnitude + a
saturate flag bit) and wire-fault/recovery tallies (int4-style: two
3-bit+flag nibbles per byte) between the header and the payload.
Exactness is load-bearing only for raft state — telemetry saturates at
the rail and flags (never silently wraps), and the raft payload bytes
are untouched (tests assert byte-identity with the summary stripped).

Frame transport: `send_frame`/`recv_frame` speak multiprocessing
Connections natively (message-oriented) and raw stream sockets via a
u32le length prefix.
"""

from __future__ import annotations

import struct

import numpy as np

from raft_tpu import config
from raft_tpu.fabric.extract import Bundle, ENT_FIELDS, SCALAR_FIELDS
from raft_tpu.fabric.placement import CHANNELS
from raft_tpu.types import MessageType as MT

MAGIC = b"RFAB"
VERSION = 1
FLAG_DIET = 0x01
FLAG_PB = 0x02
FLAG_SUM = 0x04  # quantized telemetry summary section follows the header

# magic, version, flags, n_ents(E), seq, round (EMIT round), count
_HDR = struct.Struct("<4sBBHIiI")

# -- quantized telemetry summary (satellite of the skew pipeline) ----------
#
# Fixed key tables, so a key costs one byte on the wire. int8-style
# deltas: u1 key id + u1 value (low 7 bits = magnitude clamped to 0..127,
# bit 7 = saturate flag). int4-style tallies: fixed-order vector, two
# nibbles per byte (low nibble first), each nibble = 3-bit magnitude
# clamped to 0..7 + bit 3 saturate flag. Saturation is FLAGGED, never
# wrapped: the decoder folds flags into fabric_summary_saturated.
SUMMARY_DELTA_KEYS = (
    "fabric_frames_sent",
    "fabric_frames_received",
    "fabric_msgs_exported",
    "fabric_msgs_injected",
    "fabric_msgs_total",
    "fabric_frames_staged",
    "fabric_skew_current",
)
# gauge members of the delta table: emitted as the current LEVEL, not a
# since-last-frame difference (a gauge delta can be negative, which the
# unsigned 7-bit lane cannot carry honestly)
SUMMARY_LEVEL_KEYS = (
    "fabric_frames_staged",
    "fabric_skew_current",
)
SUMMARY_TALLY_KEYS = (
    "fabric_frames_dropped",
    "fabric_frames_deferred",
    "fabric_injection_drops",
    "fabric_backpressure_rounds",
)
_SUM_LEN = struct.Struct("<H")


def pack_summary(deltas: dict, tallies: dict) -> tuple[bytes, int]:
    """-> (section_bytes, n_saturated). Unknown keys are refused (the key
    table IS the schema); negative values clamp to 0 and flag."""
    out = bytearray()
    sat = 0
    items = []
    for name, v in sorted(deltas.items()):
        if name not in SUMMARY_DELTA_KEYS:
            raise ValueError(f"unknown summary delta key {name!r}")
        v = int(v)
        q = min(max(v, 0), 127)
        s = q != v
        sat += s
        items.append((SUMMARY_DELTA_KEYS.index(name), q | (0x80 if s else 0)))
    out.append(len(items))
    for kid, b in items:
        out += bytes((kid, b))
    nibbles = []
    for name in SUMMARY_TALLY_KEYS:
        v = int(tallies.get(name, 0))
        q = min(max(v, 0), 7)
        s = q != v
        sat += s
        nibbles.append(q | (0x8 if s else 0))
    out.append(len(nibbles))
    for i in range(0, len(nibbles), 2):
        lo = nibbles[i]
        hi = nibbles[i + 1] if i + 1 < len(nibbles) else 0
        out.append(lo | (hi << 4))
    return bytes(out), sat


def unpack_summary(buf: bytes) -> tuple[dict, dict, int]:
    """-> (deltas, tallies, n_saturated); the inverse of pack_summary."""
    deltas: dict = {}
    sat = 0
    off = 0
    n = buf[off]
    off += 1
    for _ in range(n):
        kid, b = buf[off], buf[off + 1]
        off += 2
        if kid >= len(SUMMARY_DELTA_KEYS):
            raise ValueError(f"unknown summary delta key id {kid}")
        sat += bool(b & 0x80)
        deltas[SUMMARY_DELTA_KEYS[kid]] = b & 0x7F
    nt = buf[off]
    off += 1
    if nt != len(SUMMARY_TALLY_KEYS):
        raise ValueError(
            f"summary tally vector length {nt} != {len(SUMMARY_TALLY_KEYS)}"
        )
    tallies: dict = {}
    for i, name in enumerate(SUMMARY_TALLY_KEYS):
        nib = (buf[off + i // 2] >> (4 * (i % 2))) & 0xF
        sat += bool(nib & 0x8)
        tallies[name] = nib & 0x7
    off += (nt + 1) // 2
    if off != len(buf):
        raise ValueError(f"trailing bytes in fabric summary: {len(buf) - off}")
    return deltas, tallies, sat

# channel classification of decoded raftpb message types (bridge.py's
# family split: requests and responses of a family share a channel)
_FAMILY = {}
for _t in (MT.MSG_APP, MT.MSG_SNAP, MT.MSG_APP_RESP):
    _FAMILY[int(_t)] = 0
for _t in (MT.MSG_HEARTBEAT, MT.MSG_HEARTBEAT_RESP):
    _FAMILY[int(_t)] = 1
for _t in (MT.MSG_VOTE, MT.MSG_PRE_VOTE, MT.MSG_TIMEOUT_NOW):
    _FAMILY[int(_t)] = 2
for _t in (MT.MSG_VOTE_RESP, MT.MSG_PRE_VOTE_RESP):
    _FAMILY[int(_t)] = 3

# np-codec column dtypes, in fixed serialization order (chan, cell, then
# extract.SCALAR_FIELDS, then ENT_FIELDS). The diet table narrows exactly
# the fields the byte diet (state.STATE_PACK / fused.FABRIC_PACK) bounds:
# uint16 index-like columns, int8 kinds/counts, int16 entry sizes.
# context stays int32 — ReadIndex tickets are not diet-bounded.
_WIDE_DT = dict(
    chan="u1",
    cell="<u4",
    kind="<i4",
    term="<i4",
    index="<i4",
    log_term="<i4",
    commit="<i4",
    reject="<i4",
    reject_hint="<i4",
    n_ents="<i4",
    context="<i4",
    snap_index="<i4",
    snap_term="<i4",
    ent_term="<i4",
    ent_type="<i4",
    ent_bytes="<i4",
)
_DIET_DT = dict(
    _WIDE_DT,
    kind="i1",
    term="<u2",
    index="<u2",
    log_term="<u2",
    commit="<u2",
    reject="u1",
    reject_hint="<u2",
    n_ents="u1",
    snap_index="<u2",
    snap_term="<u2",
    ent_term="<u2",
    ent_type="i1",
    ent_bytes="<i2",
)
_NP_ORDER = ("chan", "cell") + SCALAR_FIELDS + ENT_FIELDS


def fabric_codec() -> str:
    """RAFT_TPU_FABRIC_CODEC: "pb" (byte-exact raftpb frames via the
    native codec) or "np" (raw columnar, diet-capable). Unset/empty =
    auto: pb when the native library loads, np otherwise."""
    return config.env_str("RAFT_TPU_FABRIC_CODEC", default="")


def fabric_diet_enabled() -> bool:
    """RAFT_TPU_FABRIC_DIET: narrow np-codec wire columns to the byte
    diet's sub-int16 bounds (requires RAFT_TPU_DIET=1 and the np codec;
    default OFF)."""
    return config.env_flag("RAFT_TPU_FABRIC_DIET", default=False)


def _native_available() -> bool:
    from raft_tpu.runtime.native import _load

    return _load() is not None


class FabricWire:
    """Per-host wire endpoint: encode outbound bundles into frames and
    decode inbound frames into bundles, counting frames/bytes into the
    driver's HostCounters (metrics/host.py FABRIC_COUNTERS)."""

    def __init__(self, n_voters: int, n_ents: int, counters=None, codec=None):
        self.v = int(n_voters)
        self.e = int(n_ents)
        self.counters = counters
        self.diet = fabric_diet_enabled()
        name = codec or fabric_codec() or ("pb" if _native_available() else "np")
        if name not in ("pb", "np"):
            raise ValueError(f"RAFT_TPU_FABRIC_CODEC must be pb|np, got {name!r}")
        if name == "pb" and not _native_available():
            raise RuntimeError(
                "RAFT_TPU_FABRIC_CODEC=pb needs the native raftpb library"
            )
        if self.diet:
            if name != "np":
                raise RuntimeError(
                    "RAFT_TPU_FABRIC_DIET requires the np codec (pb frames "
                    "are byte-exact raftpb and cannot narrow)"
                )
            if not config.env_flag("RAFT_TPU_DIET", default=False):
                raise RuntimeError(
                    "RAFT_TPU_FABRIC_DIET=1 requires RAFT_TPU_DIET=1: only "
                    "the byte diet's auto-rebase keeps index/term columns "
                    "inside the uint16 wire bounds"
                )
        self.codec = name
        self.seq = 0
        # decode side-channel: the last frame's telemetry summary
        # (deltas, tallies, n_saturated) or None — read it right after
        # decode() (the skewed driver folds it into peer_summaries)
        self.last_summary: tuple | None = None

    # -- frame encode/decode ----------------------------------------------

    def encode(self, bundle: Bundle | None, rnd: int, summary=None) -> bytes:
        """Frame the bundle under EMIT round `rnd`. `summary` (optional
        (deltas, tallies) dict pair) rides as a quantized telemetry
        section between header and payload — np codec + diet only, so the
        raft payload bytes and the pb interop format never change."""
        k = 0 if bundle is None else bundle.count
        if k == 0:
            payload = b""
        elif self.codec == "pb":
            payload = self._encode_pb(bundle)
        else:
            payload = self._encode_np(bundle)
        section = b""
        flags = (FLAG_DIET if self.diet else 0) | (
            FLAG_PB if self.codec == "pb" else 0
        )
        if summary is not None:
            if not self.diet:
                raise RuntimeError(
                    "fabric telemetry summaries require RAFT_TPU_FABRIC_DIET "
                    "(the quantized section is part of the wire diet)"
                )
            sec, sat = pack_summary(*summary)
            if sat and self.counters is not None:
                self.counters.inc("fabric_summary_saturated", sat)
            section = _SUM_LEN.pack(len(sec)) + sec
            flags |= FLAG_SUM
        frame = (
            _HDR.pack(MAGIC, VERSION, flags, self.e, self.seq, rnd, k)
            + section
            + payload
        )
        self.seq += 1
        if self.counters is not None:
            self.counters.inc("fabric_frames_sent")
            self.counters.inc("fabric_bytes_sent", len(frame))
        return frame

    def decode(self, frame: bytes) -> Bundle:
        magic, ver, flags, e, _seq, rnd, k = _HDR.unpack_from(frame, 0)
        if magic != MAGIC or ver != VERSION:
            raise ValueError("bad fabric frame header")
        off = _HDR.size
        self.last_summary = None
        if flags & FLAG_SUM:
            (slen,) = _SUM_LEN.unpack_from(frame, off)
            off += _SUM_LEN.size
            self.last_summary = unpack_summary(frame[off : off + slen])
            off += slen
        payload = frame[off:]
        if k == 0:
            b = Bundle.empty(self.e, rnd)
        elif flags & FLAG_PB:
            b = self._decode_pb(payload, k, rnd)
        else:
            b = self._decode_np(payload, k, e, bool(flags & FLAG_DIET), rnd)
        if self.counters is not None:
            self.counters.inc("fabric_frames_received")
            self.counters.inc("fabric_bytes_received", len(frame))
        return b

    # -- np payload --------------------------------------------------------

    def _encode_np(self, b: Bundle) -> bytes:
        dt = _DIET_DT if self.diet else _WIDE_DT
        parts = []
        for name in _NP_ORDER:
            x = b.chan if name == "chan" else b.cell if name == "cell" else b.cols[name]
            d = np.dtype(dt[name])
            if self.diet and d.itemsize < 4 and name not in ("chan", "cell"):
                info = np.iinfo(d)
                if x.min(initial=0) < info.min or x.max(initial=0) > info.max:
                    raise ValueError(
                        f"fabric diet overflow in {name}: values escape "
                        f"{d} — diet rebase invariant violated"
                    )
            parts.append(np.ascontiguousarray(x, dtype=d).tobytes())
        return b"".join(parts)

    def _decode_np(self, payload: bytes, k: int, e: int, diet: bool, rnd: int) -> Bundle:
        dt = _DIET_DT if diet else _WIDE_DT
        off = 0
        raw = {}
        for name in _NP_ORDER:
            d = np.dtype(dt[name])
            n = k * (e if name in ENT_FIELDS else 1)
            raw[name] = np.frombuffer(payload, d, count=n, offset=off)
            off += n * d.itemsize
        if off != len(payload):
            raise ValueError(f"trailing bytes in fabric frame: {len(payload) - off}")
        cols = {
            f: raw[f].astype(np.int32).reshape((k, e) if f in ENT_FIELDS else (k,))
            for f in SCALAR_FIELDS + ENT_FIELDS
        }
        return Bundle(raw["chan"].astype(np.uint8), raw["cell"].astype(np.uint32), cols, rnd)

    # -- pb payload (runtime/codec.py columnar frame schema) ---------------

    def _encode_pb(self, b: Bundle) -> bytes:
        from raft_tpu.runtime import codec as rcodec

        v = self.v
        k = b.count
        c = b.cols
        src_lane = b.cell.astype(np.int64) // v
        dst_lane = (src_lane // v) * v + (b.cell.astype(np.int64) % v)
        is_rep = b.chan == 0
        is_hb = b.chan == 1
        is_vote = b.chan == 2
        kind = c["kind"].astype(np.int64)
        is_snap = kind == int(MT.MSG_SNAP)

        sc = np.zeros((k, 11), np.uint64)
        sc[:, 0] = kind
        sc[:, 1] = dst_lane + 1  # global raft id of lane L is L + 1
        sc[:, 2] = src_lane + 1
        sc[:, 3] = c["term"]
        sc[:, 4] = np.where(is_rep | is_vote, c["log_term"], 0)
        sc[:, 5] = np.where(is_rep | is_vote, c["index"], 0)
        sc[:, 6] = np.where(is_rep | is_hb, c["commit"], 0)
        sc[:, 7] = np.where(is_hb | is_vote, 0, c["reject"]).astype(bool)
        sc[:, 8] = np.where(is_rep, c["reject_hint"], 0)
        sc[:, 10] = is_snap
        ctx = np.where(is_hb | is_vote, c["context"], 0).astype(np.int64)
        n_ents = np.where(is_rep, c["n_ents"], 0).astype(np.int32)

        ent_rows, ent_lens = [], []
        snap_ids = []
        for i in np.nonzero(n_ents)[0]:
            prev = int(c["index"][i])
            for j in range(int(n_ents[i])):
                ent_rows.append(
                    (int(c["ent_type"][i, j]), int(c["ent_term"][i, j]), prev + 1 + j)
                )
                ent_lens.append(int(c["ent_bytes"][i, j]))
        snap_meta = np.zeros((k, 3), np.uint64)
        snap_counts = np.zeros((k, 4), np.int32)
        if is_snap.any():
            snap_meta[:, 0] = np.where(is_snap, c["snap_index"], 0)
            snap_meta[:, 1] = np.where(is_snap, c["snap_term"], 0)
            snap_counts[:, 0] = np.where(is_snap, v, 0)
            for i in np.nonzero(is_snap)[0]:
                g = int(src_lane[i]) // v
                snap_ids.extend(g * v + j + 1 for j in range(v))
        return rcodec.pack_frame_cols(
            dict(
                scalars=sc,
                ctx=ctx,
                n_ents=n_ents,
                ent_scalars=np.array(ent_rows, np.uint64).reshape(-1, 3),
                ent_lens=np.array(ent_lens, np.int64),
                ent_data=bytes(int(sum(l for l in ent_lens if l > 0))),
                snap_meta=snap_meta,
                snap_counts=snap_counts,
                snap_ids=np.array(snap_ids, np.uint64),
            )
        )

    def _decode_pb(self, payload: bytes, k: int, rnd: int) -> Bundle:
        from raft_tpu.runtime import codec as rcodec

        cols = rcodec.unpack_frame_cols(payload)
        sc = cols["scalars"].astype(np.int64)
        if sc.shape[0] != k:
            raise ValueError(
                f"fabric frame count mismatch: header {k}, payload {sc.shape[0]}"
            )
        v = self.v
        e = self.e
        kind = sc[:, 0]
        chan = np.array([_FAMILY[int(t)] for t in kind], np.uint8)
        dst_lane = sc[:, 1] - 1
        src_lane = sc[:, 2] - 1
        cell = (src_lane * v + dst_lane % v).astype(np.uint32)
        is_rep = chan == 0
        is_hb = chan == 1
        is_vote = chan == 2
        ctx = np.maximum(cols["ctx"].astype(np.int64), 0)
        n_ents = np.where(is_rep, cols["n_ents"].astype(np.int64), 0)
        out = {
            "kind": kind,
            "term": sc[:, 3],
            "index": np.where(is_rep | is_vote, sc[:, 5], 0),
            "log_term": np.where(is_rep | is_vote, sc[:, 4], 0),
            "commit": np.where(is_rep | is_hb, sc[:, 6], 0),
            "reject": np.where(is_hb | is_vote, 0, sc[:, 7]),
            "reject_hint": np.where(is_rep, sc[:, 8], 0),
            "n_ents": n_ents,
            "context": np.where(is_hb | is_vote, ctx, 0),
            "snap_index": np.where(sc[:, 10] != 0, cols["snap_meta"][:, 0].astype(np.int64), 0),
            "snap_term": np.where(sc[:, 10] != 0, cols["snap_meta"][:, 1].astype(np.int64), 0),
        }
        ent_term = np.zeros((k, e), np.int64)
        ent_type = np.zeros((k, e), np.int64)
        ent_bytes = np.zeros((k, e), np.int64)
        ent_sc = cols["ent_scalars"].astype(np.int64)
        ent_lens = cols["ent_lens"].astype(np.int64)
        off = 0
        for i in np.nonzero(n_ents)[0]:
            n_e = int(n_ents[i])
            ent_type[i, :n_e] = ent_sc[off : off + n_e, 0]
            ent_term[i, :n_e] = ent_sc[off : off + n_e, 1]
            ent_bytes[i, :n_e] = np.maximum(ent_lens[off : off + n_e], 0)
            off += n_e
        out["ent_term"], out["ent_type"], out["ent_bytes"] = ent_term, ent_type, ent_bytes
        return Bundle(
            chan,
            cell,
            {f: np.asarray(x).astype(np.int32) for f, x in out.items()},
            rnd,
        )


# -- frame transport (length-prefixed on streams) -------------------------


def send_frame(conn, frame: bytes) -> None:
    """Message-oriented on mp.Connection, u32le length prefix on sockets."""
    if hasattr(conn, "send_bytes"):
        conn.send_bytes(frame)
    else:
        conn.sendall(struct.pack("<I", len(frame)) + frame)


def recv_frame(conn) -> bytes:
    if hasattr(conn, "recv_bytes"):
        return conn.recv_bytes()
    hdr = _recv_exact(conn, 4)
    (n,) = struct.unpack("<I", hdr)
    return _recv_exact(conn, n)


def _recv_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise EOFError("fabric peer closed")
        buf += chunk
    return buf
