"""Round-synchronous fabric drivers: lockstep coordinator + mp launcher.

Milestone-1 protocol (ROADMAP item 4): every host runs the FULL
monolithic geometry FusedCluster(G, V, seed) — identical per-lane PRNG
streams and randomized timeouts as the single-process cluster — with
non-owned lanes marked as ghosts (bridge.py idiom: own-view learner bit,
so no tick can ever campaign them). Each lockstep round is then

    inject pending frames -> run(1) -> extract cross-host cells (clear
    them) -> exchange one frame per (peer, round)

which reproduces the monolithic emit-round-r / consume-round-r+1 message
latency exactly, in both directions: a cross-host message extracted
after round r is injected at the destination before round r+1, landing
in the ghost sender's outbox cell so the next round's route transpose
delivers it like resident traffic. Owned-lane state trajectories are
therefore BIT-IDENTICAL to the monolithic run — the digest-parity
oracle tests/test_fabric.py and benches/fabric_ab.py gate on.

Persist-before-send: the fused round's synchronous persist has already
advanced `stabled` past every appended entry by the time run(1) returns
(the WAL push happens inside the round program's dispatch fence), so any
frame encoded from the post-round carry only carries messages whose
entries are locally stable — the raft thesis §10.2 ordering, inherited
rather than re-implemented.

Wire chaos (ChaosSchedule.wire_partition / wire_delay) is applied on the
SENDER side through WireGate: a dropped edge still sends an empty frame
(the frame is the round barrier), a delayed bundle is held and merged
into a later round's frame. Both drivers consult the same schedule so
in-process and multi-process runs replay identical fault timelines.

Bounded skew (RAFT_TPU_FABRIC_SKEW=D, default 0 = lockstep): the wire
contract becomes a FIXED D-round latency — a frame emitted at round r is
injected before the receiver's round r+D+1 instead of r+1 — so each host
may legally run up to D rounds ahead of its slowest peer. Frames stage
in a receive-side map keyed (peer, emit_round); the only hard block is
backpressure, when the frame due for the next round has not arrived
(i.e. a peer is more than D rounds behind). Determinism is preserved by
construction, not sacrificed: the chaos wire plane already models fixed
N-round deferral, so a skew-D fleet is bit-identical to a lockstep fleet
running chaos/schedule.skew_twin_schedule's uniform D-round wire_delay —
the sha256 fleet-digest oracle tests/test_fabric.py and
benches/fabric_ab.py gate on. Chaos composes: a user wire_delay of k
rounds defers the EMIT tag sender-side exactly as in lockstep (total
latency D+k — the commutation identity the tests pin), while a
wire_partition moves to the receiver and drops a staged bundle tagged q
iff the edge is cut at round q+D — the round the lockstep twin's
WireGate would have released (and dropped) it.

Two drivers:
  LockstepFabric     all hosts in one process (units, chaos probes,
                     per-round trajectory digests without IPC); the same
                     step/stage/inject protocol serves any skew D
  run_fabric_workers spawn one OS process per host, pairwise pipes.
                     D=0: blocking recv per (peer, round) as the barrier.
                     D>0: frame encode + socket I/O move to one sender
                     and one receiver thread per peer, so round r+1's
                     dispatch overlaps round r's frames in flight — the
                     perf payoff benches/fabric_ab.py gates under an
                     injected per-frame wire latency
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
import traceback

import numpy as np

from raft_tpu.fabric import fabric_enabled, fabric_skew
from raft_tpu.fabric.extract import (
    Bundle,
    FabricExtractor,
    merge_bundles,
    split_bundle,
)
from raft_tpu.fabric.inject import FabricInjector
from raft_tpu.fabric.placement import Placement
from raft_tpu.fabric.wire import FabricWire, recv_frame, send_frame
from raft_tpu.metrics.host import HostCounters
from raft_tpu.utils.profiling import SpanRecorder


# -- trajectory digests ----------------------------------------------------


def state_leaves(cluster) -> list:
    """Global [N]-leading numpy leaves of a cluster's slim-canonical
    host_state, concatenating blocks in lane order for blocked clusters —
    the digest's byte source (jax.tree leaf order is deterministic)."""
    import jax

    blocks = getattr(cluster, "blocks", None)
    if not blocks:
        return [np.asarray(x) for x in jax.tree.leaves(cluster.host_state())]
    per = [
        [np.asarray(x) for x in jax.tree.leaves(b.host_state())]
        for b in blocks
    ]
    return [np.concatenate([rows[i] for rows in per]) for i in range(len(per[0]))]


def owned_rows(cluster, own: np.ndarray) -> list:
    """The host's owned-lane slice of every state leaf."""
    own = np.asarray(own)
    return [leaf[own] for leaf in state_leaves(cluster)]


class TrajectoryDigest:
    """Chained per-round sha256 over a fixed lane subset's state leaves.
    A multi-host run hashes each host's OWNED rows independently (no
    cross-process stitching needed); the monolithic twin reproduces each
    host's chain by masking its own global state with that host's own
    mask at the same round boundaries. fleet_digest folds the per-host
    chains into the single run digest the oracles compare."""

    def __init__(self):
        self._h = hashlib.sha256()

    def update(self, rows) -> None:
        for r in rows:
            self._h.update(np.ascontiguousarray(r).tobytes())

    def hex(self) -> str:
        return self._h.hexdigest()


def fleet_digest(host_hexes) -> str:
    h = hashlib.sha256()
    for x in host_hexes:
        h.update(bytes.fromhex(x))
    return h.hexdigest()


def mono_fleet_digest(cluster, placement, rounds, ops_spec=None, **run_kw) -> str:
    """Run the monolithic twin round by round and fold the per-host-mask
    trajectory chains exactly like the fabric drivers do. `cluster` is a
    FusedCluster or BlockedFusedCluster on the same (G, V, seed)."""
    tds = [TrajectoryDigest() for _ in range(placement.n_hosts)]
    masks = [placement.own_mask(h) for h in range(placement.n_hosts)]
    for r in range(rounds):
        ops = cluster.ops(**ops_spec) if (ops_spec and r == 0) else None
        cluster.run(1, ops=ops, **run_kw)
        leaves = state_leaves(cluster)
        for td, own in zip(tds, masks):
            td.update([leaf[own] for leaf in leaves])
    return fleet_digest([td.hex() for td in tds])


# -- ops + ghost plumbing --------------------------------------------------


def _filter_ops_spec(spec: dict, own: np.ndarray) -> dict:
    """Restrict a {field: {lane: value}} ops spec to owned lanes. Specs
    are dict-of-dicts only (the make_local_ops dict form): ghosts must
    never receive local ops, and the owner applies the identical value
    the monolithic twin does."""
    out = {}
    for field, lanes in spec.items():
        if not isinstance(lanes, dict):
            raise TypeError(
                f"fabric ops spec field {field!r} must be a dict of "
                "{lane: value} so it can be split by owner"
            )
        kept = {ln: v for ln, v in lanes.items() if own[int(ln)]}
        if kept:
            out[field] = kept
    return out


def _mark_ghosts(cl, ghost: np.ndarray, v: int) -> None:
    """bridge.py's ghost idiom on a built FusedCluster: set the ghost's
    learner bit in its OWN learners row (promotable() reads the mask at
    the self slot, so no tick can ever campaign it) plus the is_learner
    mirror; other lanes' masks are untouched and still count the member
    as a voter. Diet-aware: mutate the unpacked view, restore the packed
    layout."""
    import jax.numpy as jnp

    from raft_tpu.state import is_packed, pack_state, unpack_state

    packed = is_packed(cl.state)
    st = unpack_state(cl.state)
    lanes = np.nonzero(ghost)[0]
    lrn = np.asarray(st.learners).copy()
    lrn[lanes, lanes % v] = True
    st = dataclasses.replace(
        st,
        learners=jnp.asarray(lrn, dtype=st.learners.dtype),
        is_learner=jnp.asarray(
            np.asarray(st.is_learner) | ghost, dtype=st.is_learner.dtype
        ),
    )
    cl.state = pack_state(st) if packed else st


# -- wire chaos gate -------------------------------------------------------


class WireGate:
    """Sender-side wire fault application (ChaosSchedule wire plane).
    Deterministic by construction: both drivers consult the same absolute
    round, and faults never depend on payload contents.

    sender_drop=False (the skewed driver) keeps the delay machinery —
    user wire_delays still defer the emit tag here, preserving the
    skew + delay commutation identity — but leaves wire_partition drops
    to the receiver, which cuts a staged bundle tagged q iff the edge is
    down at round q+D: the exact round a lockstep gate would have
    released (and therefore drop-checked) it."""

    def __init__(self, schedule, counters: HostCounters, n_ents: int,
                 sender_drop: bool = True):
        self.schedule = schedule
        self.counters = counters
        self.e = n_ents
        self.sender_drop = sender_drop
        self._held: dict = {}  # (src, dst) -> [(release_round, Bundle)]

    def outbound(self, rnd: int, src: int, dst: int, bundle) -> Bundle:
        """Gate one edge's outbound bundle at round `rnd` -> the bundle to
        put on this round's frame (empty when dropped/deferred; deferred
        bundles from earlier rounds merge in once due)."""
        edge = (src, dst)
        held = self._held.setdefault(edge, [])
        ready = [b for rel, b in held if rel <= rnd]
        held[:] = [(rel, b) for rel, b in held if rel > rnd]
        if self.schedule is None:
            return merge_bundles([bundle] + ready, self.e, rnd)
        plan = self.schedule.wire_plan(rnd)
        d = plan["delay"].get(edge, 0)
        if d and bundle is not None and bundle.count:
            held.append((rnd + d, bundle))
            self.counters.inc("fabric_frames_deferred")
            bundle = None
        out = merge_bundles([bundle] + ready, self.e, rnd)
        if self.sender_drop and edge in plan["drop"]:
            if out.count:
                self.counters.inc("fabric_frames_dropped")
            out = Bundle.empty(self.e, rnd)
        return out


# -- one host's view -------------------------------------------------------


class FabricHost:
    """One host's slice of the fleet: the full-geometry engine with ghost
    lanes, the extract/inject endpoints, the wire codec, counters, spans,
    and (optionally) the owned-lane trajectory chain."""

    def __init__(
        self,
        placement: Placement,
        host: int,
        seed: int = 1,
        shape=None,
        cap: int | None = None,
        schedule=None,
        track_trajectory: bool = False,
        **cfg,
    ):
        if not fabric_enabled():
            raise RuntimeError(
                "cross-host fabric is disabled: set RAFT_TPU_FABRIC=1"
            )
        from raft_tpu.ops.fused import FusedCluster

        self.placement = placement
        self.host = int(host)
        self.cl = FusedCluster(
            placement.n_groups, placement.n_voters, seed=seed, shape=shape, **cfg
        )
        self.v = placement.n_voters
        self.e = int(self.cl.fab.rep.ent_term.shape[-1])
        self.own = placement.own_mask(host)
        if (~self.own).any():
            _mark_ghosts(self.cl, ~self.own, self.v)
        self.counters = HostCounters()
        # pre-seed the full fabric family so snapshots export a stable
        # schema (a zero drop counter is a signal, not a missing series)
        from raft_tpu.metrics.host import FABRIC_COUNTERS

        for name in FABRIC_COUNTERS:
            self.counters.inc(name, 0)
        self.spans = SpanRecorder()
        self.extractor = FabricExtractor(placement, host, cap)
        self.injector = FabricInjector(placement, host, cap)
        self.wire = FabricWire(self.v, self.e, counters=self.counters)
        self.skew = fabric_skew()
        self.gate = WireGate(
            schedule, self.counters, self.e, sender_drop=(self.skew == 0)
        )
        self.peers = placement.peers(host)
        self.trajectory = TrajectoryDigest() if track_trajectory else None
        self._pending: list = []
        # skew mode: frames parked until D+1 rounds past their emit tag.
        # Single-op dict access only (receive adds, _collect_due pops
        # distinct keys), so the GIL is the synchronization the worker's
        # per-peer receiver threads rely on.
        self._staging: dict = {}  # (peer, emit_round) -> Bundle
        self._peer_emit = {p: -1 for p in self.peers}  # max tag seen
        # telemetry summaries (RAFT_TPU_FABRIC_DIET + skew): per-peer
        # counter values at last emit (delta base) and the accumulated
        # decoded summaries from each peer
        self._sum_prev: dict = {p: {} for p in self.peers}
        self.peer_summaries: dict = {p: {} for p in self.peers}
        self.round = 0

    # -- one round ---------------------------------------------------------

    def _collect_due(self) -> list:
        """Skew mode: pop this round's due staged bundles — emit tag
        round-D-1, one per peer (their presence is the skew contract; a
        hole means the caller failed to backpressure). The receiver-side
        wire_partition check happens HERE, at round due+D — the round the
        lockstep twin's sender gate would have released (and dropped) the
        bundle — so chaos timelines compose identically under skew."""
        due = self.round - self.skew - 1
        if due < 0:
            return []
        bundles = []
        sched = self.gate.schedule
        plan = sched.wire_plan(due + self.skew) if sched is not None else None
        for p in self.peers:
            b = self._staging.pop((p, due), None)
            if b is None:
                raise RuntimeError(
                    f"fabric skew underrun: host {self.host} entering round "
                    f"{self.round} without frame ({p}, {due}) staged — the "
                    "driver must block (backpressure) until it arrives"
                )
            if plan is not None and (p, self.host) in plan["drop"]:
                if b.count:
                    self.counters.inc("fabric_frames_dropped")
                continue
            if b.count:
                bundles.append(b)
        return bundles

    def _step_core(self, ops_spec=None, **run_kw) -> tuple:
        """Inject due bundles -> run(1) -> extract -> gate. Returns
        (emit_round, {peer: Bundle}) with one outbound bundle per peer
        (possibly empty — the frame is the liveness token either way);
        encode/transport is the caller's half, so the skewed worker can
        move it onto per-peer threads."""
        rnd = self.round
        pending = self._pending + (self._collect_due() if self.skew else [])
        self._pending = []
        merged = merge_bundles(pending, self.e, rnd)
        if merged.count:
            fab, injected, dropped = self.injector(self.cl.fab, merged)
            self.cl.fab = fab
            self.counters.inc("fabric_msgs_injected", injected)
            if dropped:
                self.counters.inc("fabric_injection_drops", dropped)
        ops = None
        if ops_spec:
            kept = _filter_ops_spec(ops_spec, self.own)
            if kept:
                ops = self.cl.ops(**kept)
        self.cl.run(1, ops=ops, **run_kw)
        fab, bundle, total = self.extractor(self.cl.fab, rnd)
        if bundle is not None:
            self.cl.fab = fab
            self.counters.inc("fabric_msgs_exported", bundle.count)
        self.counters.inc("fabric_msgs_total", int(total))
        parts = split_bundle(bundle, self.placement, self.e)
        outs = {
            p: self.gate.outbound(rnd, self.host, p, parts.get(p))
            for p in self.peers
        }
        if self.trajectory is not None:
            self.trajectory.update(owned_rows(self.cl, self.own))
        self.round += 1
        if self.skew:
            # completed-round gap to the slowest peer's last emit: 0 in
            # perfect lockstep, D at the backpressure edge
            behind = min(self._peer_emit.values(), default=rnd - 1)
            cur = max(0, rnd - 1 - behind)
            self.counters.set("fabric_skew_current", cur)
            self.counters.set_max("fabric_skew_max", cur)
            self.counters.set("fabric_frames_staged", len(self._staging))
        return rnd, outs

    def step(self, ops_spec=None, **run_kw) -> dict:
        """One round, synchronous transport: _step_core + encode. Returns
        {peer: frame_bytes}, ALWAYS one frame per peer (empty frames are
        the round barrier / skew liveness token). ops_spec is the global
        {field: {lane: value}} dict, filtered to owned lanes here (the
        mono twin applies it whole)."""
        rnd, outs = self._step_core(ops_spec, **run_kw)
        frames = {}
        for p in self.peers:
            frames[p] = self.encode_frame(p, outs[p], rnd)
        return frames

    def encode_frame(self, peer: int, out: Bundle, rnd: int) -> bytes:
        """Encode one peer's gated outbound bundle (+ telemetry summary
        when the diet + skew planes are on) and record its tx span."""
        frame = self.wire.encode(out, rnd, summary=self.emit_summary(peer))
        if out.count:
            self.spans.spans.append((
                "fabric_tx", time.perf_counter(), 0.0,
                dict(round=rnd, peer=peer, msgs=out.count,
                     bytes=len(frame), groups=self._groups_of(out)),
            ))
        return frame

    def receive(self, frame: bytes, peer: int = -1, wire=None) -> None:
        """Decoded frames become injections (bridge IMPORT): immediately
        pending in lockstep, staged under (peer, emit_round) with skew.

        The emit tag is VALIDATED against the staging window rather than
        trusted: lockstep accepts exactly round-1 (the barrier contract);
        skew D accepts [round-D-1, round+D+1] (the +1 absorbs the benign
        race with the main loop's round increment) and refuses duplicate
        (peer, tag) slots. Out-of-window frames count fabric_frames_dropped
        with a rate-limited warning instead of silently merging — a stale
        or replayed frame can never scribble on a live round. `wire`
        overrides the decode endpoint (the skewed worker gives each
        receiver thread its own, so seq/summary state is per-peer)."""
        from raft_tpu.logging import warn_rate_limited

        w = wire or self.wire
        b = w.decode(frame)
        if w.last_summary is not None:
            self._fold_summary(peer, w.last_summary)
        tag = int(b.round)
        lo = self.round - self.skew - 1
        hi = (self.round - 1) if self.skew == 0 else (self.round + self.skew + 1)
        bad = not lo <= tag <= hi
        if not bad and self.skew and (peer, tag) in self._staging:
            bad = True
        if bad:
            self.counters.inc("fabric_frames_dropped")
            warn_rate_limited(
                f"fabric_window_{self.host}", 5.0,
                "fabric host %d: frame from peer %d with emit round %d "
                "outside staging window [%d, %d] (or duplicate) — dropped",
                self.host, peer, tag, lo, hi,
            )
            return
        if b.count:
            self.spans.spans.append((
                "fabric_rx", time.perf_counter(), 0.0,
                dict(round=tag, peer=peer, msgs=b.count,
                     bytes=len(frame), groups=self._groups_of(b)),
            ))
        if self.skew == 0:
            if b.count:
                self._pending.append(b)
            return
        self._staging[(peer, tag)] = b
        if tag > self._peer_emit.get(peer, -1):
            self._peer_emit[peer] = tag

    # -- quantized telemetry summaries (RAFT_TPU_FABRIC_DIET + skew) -------

    def emit_summary(self, peer: int):
        """(deltas, tallies) of this host's counters since the last frame
        to `peer`, or None when the summary plane is off. Skew-gated so
        the D=0 wire stays byte-identical to the lockstep milestone."""
        if not (self.wire.diet and self.skew):
            return None
        from raft_tpu.fabric.wire import (
            SUMMARY_DELTA_KEYS,
            SUMMARY_LEVEL_KEYS,
            SUMMARY_TALLY_KEYS,
        )

        prev = self._sum_prev[peer]
        cur = {
            k: self.counters.get(k)
            for k in SUMMARY_DELTA_KEYS + SUMMARY_TALLY_KEYS
        }
        deltas = {
            k: cur[k] if k in SUMMARY_LEVEL_KEYS else cur[k] - prev.get(k, 0)
            for k in SUMMARY_DELTA_KEYS
        }
        tallies = {k: cur[k] - prev.get(k, 0) for k in SUMMARY_TALLY_KEYS}
        self._sum_prev[peer] = cur
        return deltas, tallies

    def _fold_summary(self, peer: int, summary: tuple) -> None:
        from raft_tpu.fabric.wire import SUMMARY_LEVEL_KEYS

        deltas, tallies, sat = summary
        acc = self.peer_summaries.setdefault(peer, {})
        for name, v in list(deltas.items()) + list(tallies.items()):
            if name in SUMMARY_LEVEL_KEYS:
                acc[name] = int(v)  # gauge: latest level wins
            else:
                acc[name] = acc.get(name, 0) + int(v)
        if sat:
            self.counters.inc("fabric_summary_saturated", sat)

    def _groups_of(self, bundle: Bundle) -> tuple:
        vv = self.v * self.v
        return tuple(sorted({int(c) // vv for c in bundle.cell}))

    # -- observability -----------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """Fabric counters folded with the engine's device snapshot (when
        RAFT_TPU_METRICS=1), mirrored process-wide for /metrics exports."""
        from raft_tpu.metrics.host import merge_snapshots, record_fabric_stats

        record_fabric_stats(self.counters.counts)
        snaps = [self.counters.snapshot()]
        eng = self.cl.metrics_snapshot()
        if eng is not None:
            snaps.append(eng)
        return merge_snapshots(snaps)


# -- in-process lockstep coordinator ---------------------------------------


class LockstepFabric:
    """All hosts of a placement in one process, stepped in lockstep —
    the unit-test / chaos-probe driver (no IPC, same protocol and same
    WireGate semantics as the spawned workers). The loop is skew-agnostic:
    under RAFT_TPU_FABRIC_SKEW=D every frame delivered at iteration r
    stages under its emit tag and each host pops tag r-D-1 on its next
    step, so this driver doubles as the deterministic delay-model twin
    the multi-process skew oracle compares against."""

    def __init__(self, placement: Placement, seed: int = 1, **host_kw):
        self.placement = placement
        self.hosts = [
            FabricHost(placement, h, seed=seed, **host_kw)
            for h in range(placement.n_hosts)
        ]
        self.round = 0

    def run(self, rounds: int = 1, ops_spec=None, **run_kw) -> "LockstepFabric":
        for i in range(rounds):
            spec = ops_spec if i == 0 else None
            frames = {fh.host: fh.step(spec, **run_kw) for fh in self.hosts}
            for src, out in frames.items():
                for dst, frame in out.items():
                    self.hosts[dst].receive(frame, peer=src)
            self.round += 1
        return self

    # -- stitched inspection ----------------------------------------------

    def state_columns(self, *names) -> dict:
        """Global columns stitched from each host's owned lanes."""
        out = {}
        for name in names:
            full = None
            for fh in self.hosts:
                col = fh.cl.state_columns(name)[name]
                if full is None:
                    full = np.zeros_like(col)
                full[fh.own] = col[fh.own]
            out[name] = full
        return out

    def leader_lanes(self) -> np.ndarray:
        from raft_tpu.types import StateType

        st = self.state_columns("state")["state"]
        return np.nonzero(st == int(StateType.LEADER))[0]

    def digest(self) -> str:
        """Stitched digest of the CURRENT state (end-state oracle)."""
        h = hashlib.sha256()
        parts = [(fh.own, owned_rows(fh.cl, fh.own)) for fh in self.hosts]
        n = self.placement.n_lanes
        for i in range(len(parts[0][1])):
            sample = parts[0][1][i]
            full = np.zeros((n,) + sample.shape[1:], sample.dtype)
            for own, rows in parts:
                full[own] = rows[i]
            h.update(np.ascontiguousarray(full).tobytes())
        return h.hexdigest()

    def fleet_trajectory(self) -> str:
        """fleet_digest over the hosts' chained trajectories (needs
        track_trajectory=True)."""
        return fleet_digest([fh.trajectory.hex() for fh in self.hosts])

    def metrics_snapshot(self) -> dict:
        from raft_tpu.metrics.host import merge_snapshots

        return merge_snapshots([fh.metrics_snapshot() for fh in self.hosts])

    def check_no_errors(self) -> None:
        for fh in self.hosts:
            fh.cl.check_no_errors()


# -- multiprocess launcher -------------------------------------------------


def _lockstep_worker_loop(fh: FabricHost, conns: dict, cfg: dict) -> list:
    """D=0: the milestone-1 protocol, byte-identical to PR 18 — blocking
    recv per (peer, round) IS the round barrier. An injected per-frame
    wire latency (benches) sleeps on the critical path: the whole point
    of the skewed pipeline is to move it off."""
    lat = float(cfg.get("wire_latency") or 0.0)
    sleep = float((cfg.get("straggle") or {}).get(fh.host, 0.0))
    marks = []
    for r in range(cfg["rounds"]):
        marks.append(time.perf_counter())
        if sleep:
            time.sleep(sleep)
        spec = cfg.get("ops_spec") if r == 0 else None
        frames = fh.step(spec, **cfg.get("run_kw") or {})
        if lat:
            time.sleep(lat)  # frames spend `lat` seconds in flight
        for p, frame in frames.items():
            send_frame(conns[p], frame)
        for p in fh.peers:
            fh.receive(recv_frame(conns[p]), peer=p)
    marks.append(time.perf_counter())
    return marks


def _skewed_worker_loop(fh: FabricHost, conns: dict, cfg: dict) -> list:
    """D>0: frame encode and socket I/O live on one sender + one receiver
    thread per peer, each with its own FabricWire endpoint; the main
    thread only dispatches rounds and stages/pops bundles. The wire
    latency model is an absolute deadline (enqueue time + lat) so frames
    pipeline like a real link — latency, not serialization. The only
    block is backpressure: the frame due for the next round (emit tag
    round-D-1) has not arrived, i.e. a peer runs more than D behind."""
    import queue as _queue
    import threading

    lat = float(cfg.get("wire_latency") or 0.0)
    sleep = float((cfg.get("straggle") or {}).get(fh.host, 0.0))
    cond = threading.Condition()
    send_qs = {p: _queue.SimpleQueue() for p in fh.peers}
    eof = set()
    _STOP = object()

    def _sender(p, wire):
        while True:
            item = send_qs[p].get()
            if item is _STOP:
                return
            out, rnd, summary, t_enq = item
            frame = wire.encode(out, rnd, summary=summary)
            if out.count:
                fh.spans.spans.append((
                    "fabric_tx", time.perf_counter(), 0.0,
                    dict(round=rnd, peer=p, msgs=out.count,
                         bytes=len(frame), groups=fh._groups_of(out)),
                ))
            if lat:
                delay = t_enq + lat - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            send_frame(conns[p], frame)

    def _receiver(p, wire):
        while True:
            try:
                frame = recv_frame(conns[p])
            except (EOFError, OSError):
                with cond:
                    eof.add(p)
                    cond.notify_all()
                return
            with cond:
                fh.receive(frame, peer=p, wire=wire)
                cond.notify_all()

    senders = [
        threading.Thread(
            target=_sender,
            args=(p, FabricWire(fh.v, fh.e, counters=fh.counters)),
            daemon=True,
        )
        for p in fh.peers
    ]
    for p, t in zip(fh.peers, senders):
        t.start()
        threading.Thread(
            target=_receiver,
            args=(p, FabricWire(fh.v, fh.e, counters=fh.counters)),
            daemon=True,
        ).start()

    marks = []
    d = fh.skew
    for r in range(cfg["rounds"]):
        marks.append(time.perf_counter())
        if sleep:
            time.sleep(sleep)
        due = r - d - 1
        if due >= 0:
            late = [p for p in fh.peers if (p, due) not in fh._staging]
            if late:
                fh.counters.inc("fabric_backpressure_rounds")
                t0 = time.perf_counter()
                with cond:
                    while any(
                        (p, due) not in fh._staging for p in fh.peers
                    ):
                        dead = [
                            p for p in fh.peers
                            if p in eof and (p, due) not in fh._staging
                        ]
                        if dead:
                            raise RuntimeError(
                                f"fabric host {fh.host}: peers {dead} hung "
                                f"up before frame round {due}"
                            )
                        cond.wait(timeout=1.0)
                dur = time.perf_counter() - t0
                for p in late:
                    fh.spans.spans.append((
                        "fabric_wait", t0, dur,
                        dict(round=r, peer=p,
                             ms=round(dur * 1e3, 3),
                             groups=fh.placement.shared_groups(fh.host, p)),
                    ))
        spec = cfg.get("ops_spec") if r == 0 else None
        rnd, outs = fh._step_core(spec, **cfg.get("run_kw") or {})
        t_enq = time.perf_counter()
        for p in fh.peers:
            send_qs[p].put((outs[p], rnd, fh.emit_summary(p), t_enq))
    marks.append(time.perf_counter())
    # drain: peers may still need our last frames as liveness tokens
    for p in fh.peers:
        send_qs[p].put(_STOP)
    for t in senders:
        t.join(timeout=60)
    return marks


def _fabric_worker(host_id: int, placement: Placement, conns: dict, result, cfg: dict):
    """One spawned host process: `rounds` fabric rounds against pipe
    peers — lockstep (RAFT_TPU_FABRIC_SKEW=0, the recv barrier) or the
    bounded-skew pipeline (D>0, per-peer wire threads + backpressure)."""
    try:
        fh = FabricHost(
            placement,
            host_id,
            seed=cfg["seed"],
            cap=cfg.get("cap"),
            schedule=cfg.get("schedule"),
            track_trajectory=True,
            **cfg.get("cluster_cfg") or {},
        )
        # compile the injection scatter NOW: under skew the first real
        # injection lands at round D+1, inside the timing window, and a
        # mid-run XLA compile would swamp the per-round signal
        fh.injector.warmup(fh.cl.fab)
        loop = _skewed_worker_loop if fh.skew else _lockstep_worker_loop
        marks = loop(fh, conns, cfg)
        # steady-state per-round wall clock: median round duration past
        # the warmup rounds — robust to residual one-off stalls (a peer's
        # compile, an OS scheduling hiccup) that the mean would smear
        # across the whole run
        warm = min(4, len(marks) - 2)
        diffs = np.diff(np.asarray(marks[warm:]))
        per_round = float(np.median(diffs)) if diffs.size else 0.0
        own = fh.own
        leaders = [int(x) for x in fh.cl.leader_lanes() if own[int(x)]]
        cols = fh.cl.state_columns("state", "term", "committed", "lead")
        result.put(
            dict(
                host=host_id,
                own=own,
                rows=owned_rows(fh.cl, own),
                digest=fh.trajectory.hex(),
                counters=dict(fh.counters.counts),
                leaders=leaders,
                columns={k: v for k, v in cols.items()},
                n_spans=len(fh.spans.spans),
                per_round_s=per_round,
            )
        )
    except Exception:
        result.put(dict(host=host_id, error=traceback.format_exc()))


def run_fabric_workers(
    placement: Placement,
    *,
    rounds: int,
    seed: int = 1,
    ops_spec=None,
    run_kw=None,
    schedule=None,
    cap=None,
    cluster_cfg=None,
    timeout: float = 600.0,
    wire_latency: float = 0.0,
    straggle: dict | None = None,
) -> list:
    """Fork one worker process per host (spawn context — children inherit
    the parent's RAFT_TPU_* env, including RAFT_TPU_FABRIC_SKEW), wire
    pairwise pipes between fabric peers, run `rounds` rounds, and return
    the per-host result dicts (own mask, owned state rows, trajectory
    digest, counters, leaders, state columns, per-round wall clock) in
    host order.

    wire_latency: seconds each frame spends in flight (bench/test knob —
    on the critical path at skew 0, overlapped by the pipeline at D>0).
    straggle: {host: seconds} slept at the top of each of that host's
    rounds (the straggler-soak knob: everyone else runs ahead within the
    skew bound, then backpressures)."""
    if not fabric_enabled():
        raise RuntimeError("cross-host fabric is disabled: set RAFT_TPU_FABRIC=1")
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    n = placement.n_hosts
    conns: dict = {h: {} for h in range(n)}
    for a in range(n):
        for b in placement.peers(a):
            if b > a:
                ca, cb = ctx.Pipe()
                conns[a][b] = ca
                conns[b][a] = cb
    q = ctx.Queue()
    cfg = dict(
        seed=seed,
        rounds=rounds,
        ops_spec=ops_spec,
        run_kw=run_kw,
        schedule=schedule,
        cap=cap,
        cluster_cfg=cluster_cfg,
        wire_latency=wire_latency,
        straggle=straggle,
    )
    procs = [
        ctx.Process(
            target=_fabric_worker,
            args=(h, placement, conns[h], q, cfg),
            daemon=True,
        )
        for h in range(n)
    ]
    for p in procs:
        p.start()
    results: dict = {}
    deadline = time.time() + timeout
    try:
        while len(results) < n:
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError(
                    f"fabric workers timed out; got {sorted(results)} of {n}"
                )
            r = q.get(timeout=remaining)
            if "error" in r:
                raise RuntimeError(
                    f"fabric worker {r['host']} failed:\n{r['error']}"
                )
            results[r["host"]] = r
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return [results[h] for h in range(n)]


def workers_fleet_digest(results) -> str:
    """fleet_digest over worker results (host order)."""
    return fleet_digest([r["digest"] for r in results])


def stitched_columns(results, n_lanes: int) -> dict:
    """Global state columns stitched from worker results."""
    out: dict = {}
    for r in results:
        own = np.asarray(r["own"])
        for name, col in r["columns"].items():
            if name not in out:
                out[name] = np.zeros_like(col)
            out[name][own] = col[own]
    return out
