"""Round-synchronous fabric drivers: lockstep coordinator + mp launcher.

Milestone-1 protocol (ROADMAP item 4): every host runs the FULL
monolithic geometry FusedCluster(G, V, seed) — identical per-lane PRNG
streams and randomized timeouts as the single-process cluster — with
non-owned lanes marked as ghosts (bridge.py idiom: own-view learner bit,
so no tick can ever campaign them). Each lockstep round is then

    inject pending frames -> run(1) -> extract cross-host cells (clear
    them) -> exchange one frame per (peer, round)

which reproduces the monolithic emit-round-r / consume-round-r+1 message
latency exactly, in both directions: a cross-host message extracted
after round r is injected at the destination before round r+1, landing
in the ghost sender's outbox cell so the next round's route transpose
delivers it like resident traffic. Owned-lane state trajectories are
therefore BIT-IDENTICAL to the monolithic run — the digest-parity
oracle tests/test_fabric.py and benches/fabric_ab.py gate on.

Persist-before-send: the fused round's synchronous persist has already
advanced `stabled` past every appended entry by the time run(1) returns
(the WAL push happens inside the round program's dispatch fence), so any
frame encoded from the post-round carry only carries messages whose
entries are locally stable — the raft thesis §10.2 ordering, inherited
rather than re-implemented.

Wire chaos (ChaosSchedule.wire_partition / wire_delay) is applied on the
SENDER side through WireGate: a dropped edge still sends an empty frame
(the frame is the round barrier), a delayed bundle is held and merged
into a later round's frame. Both drivers consult the same schedule so
in-process and multi-process runs replay identical fault timelines.

Two drivers:
  LockstepFabric     all hosts in one process (units, chaos probes,
                     per-round trajectory digests without IPC)
  run_fabric_workers spawn one OS process per host, pairwise pipes,
                     blocking recv per (peer, round) as the barrier —
                     the real multi-process milestone artifact
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
import traceback

import numpy as np

from raft_tpu.fabric import fabric_enabled
from raft_tpu.fabric.extract import (
    Bundle,
    FabricExtractor,
    merge_bundles,
    split_bundle,
)
from raft_tpu.fabric.inject import FabricInjector
from raft_tpu.fabric.placement import Placement
from raft_tpu.fabric.wire import FabricWire, recv_frame, send_frame
from raft_tpu.metrics.host import HostCounters
from raft_tpu.utils.profiling import SpanRecorder


# -- trajectory digests ----------------------------------------------------


def state_leaves(cluster) -> list:
    """Global [N]-leading numpy leaves of a cluster's slim-canonical
    host_state, concatenating blocks in lane order for blocked clusters —
    the digest's byte source (jax.tree leaf order is deterministic)."""
    import jax

    blocks = getattr(cluster, "blocks", None)
    if not blocks:
        return [np.asarray(x) for x in jax.tree.leaves(cluster.host_state())]
    per = [
        [np.asarray(x) for x in jax.tree.leaves(b.host_state())]
        for b in blocks
    ]
    return [np.concatenate([rows[i] for rows in per]) for i in range(len(per[0]))]


def owned_rows(cluster, own: np.ndarray) -> list:
    """The host's owned-lane slice of every state leaf."""
    own = np.asarray(own)
    return [leaf[own] for leaf in state_leaves(cluster)]


class TrajectoryDigest:
    """Chained per-round sha256 over a fixed lane subset's state leaves.
    A multi-host run hashes each host's OWNED rows independently (no
    cross-process stitching needed); the monolithic twin reproduces each
    host's chain by masking its own global state with that host's own
    mask at the same round boundaries. fleet_digest folds the per-host
    chains into the single run digest the oracles compare."""

    def __init__(self):
        self._h = hashlib.sha256()

    def update(self, rows) -> None:
        for r in rows:
            self._h.update(np.ascontiguousarray(r).tobytes())

    def hex(self) -> str:
        return self._h.hexdigest()


def fleet_digest(host_hexes) -> str:
    h = hashlib.sha256()
    for x in host_hexes:
        h.update(bytes.fromhex(x))
    return h.hexdigest()


def mono_fleet_digest(cluster, placement, rounds, ops_spec=None, **run_kw) -> str:
    """Run the monolithic twin round by round and fold the per-host-mask
    trajectory chains exactly like the fabric drivers do. `cluster` is a
    FusedCluster or BlockedFusedCluster on the same (G, V, seed)."""
    tds = [TrajectoryDigest() for _ in range(placement.n_hosts)]
    masks = [placement.own_mask(h) for h in range(placement.n_hosts)]
    for r in range(rounds):
        ops = cluster.ops(**ops_spec) if (ops_spec and r == 0) else None
        cluster.run(1, ops=ops, **run_kw)
        leaves = state_leaves(cluster)
        for td, own in zip(tds, masks):
            td.update([leaf[own] for leaf in leaves])
    return fleet_digest([td.hex() for td in tds])


# -- ops + ghost plumbing --------------------------------------------------


def _filter_ops_spec(spec: dict, own: np.ndarray) -> dict:
    """Restrict a {field: {lane: value}} ops spec to owned lanes. Specs
    are dict-of-dicts only (the make_local_ops dict form): ghosts must
    never receive local ops, and the owner applies the identical value
    the monolithic twin does."""
    out = {}
    for field, lanes in spec.items():
        if not isinstance(lanes, dict):
            raise TypeError(
                f"fabric ops spec field {field!r} must be a dict of "
                "{lane: value} so it can be split by owner"
            )
        kept = {ln: v for ln, v in lanes.items() if own[int(ln)]}
        if kept:
            out[field] = kept
    return out


def _mark_ghosts(cl, ghost: np.ndarray, v: int) -> None:
    """bridge.py's ghost idiom on a built FusedCluster: set the ghost's
    learner bit in its OWN learners row (promotable() reads the mask at
    the self slot, so no tick can ever campaign it) plus the is_learner
    mirror; other lanes' masks are untouched and still count the member
    as a voter. Diet-aware: mutate the unpacked view, restore the packed
    layout."""
    import jax.numpy as jnp

    from raft_tpu.state import is_packed, pack_state, unpack_state

    packed = is_packed(cl.state)
    st = unpack_state(cl.state)
    lanes = np.nonzero(ghost)[0]
    lrn = np.asarray(st.learners).copy()
    lrn[lanes, lanes % v] = True
    st = dataclasses.replace(
        st,
        learners=jnp.asarray(lrn, dtype=st.learners.dtype),
        is_learner=jnp.asarray(
            np.asarray(st.is_learner) | ghost, dtype=st.is_learner.dtype
        ),
    )
    cl.state = pack_state(st) if packed else st


# -- wire chaos gate -------------------------------------------------------


class WireGate:
    """Sender-side wire fault application (ChaosSchedule wire plane).
    Deterministic by construction: both drivers consult the same absolute
    round, and faults never depend on payload contents."""

    def __init__(self, schedule, counters: HostCounters, n_ents: int):
        self.schedule = schedule
        self.counters = counters
        self.e = n_ents
        self._held: dict = {}  # (src, dst) -> [(release_round, Bundle)]

    def outbound(self, rnd: int, src: int, dst: int, bundle) -> Bundle:
        """Gate one edge's outbound bundle at round `rnd` -> the bundle to
        put on this round's frame (empty when dropped/deferred; deferred
        bundles from earlier rounds merge in once due)."""
        edge = (src, dst)
        held = self._held.setdefault(edge, [])
        ready = [b for rel, b in held if rel <= rnd]
        held[:] = [(rel, b) for rel, b in held if rel > rnd]
        if self.schedule is None:
            return merge_bundles([bundle] + ready, self.e, rnd)
        plan = self.schedule.wire_plan(rnd)
        d = plan["delay"].get(edge, 0)
        if d and bundle is not None and bundle.count:
            held.append((rnd + d, bundle))
            self.counters.inc("fabric_frames_deferred")
            bundle = None
        out = merge_bundles([bundle] + ready, self.e, rnd)
        if edge in plan["drop"]:
            if out.count:
                self.counters.inc("fabric_frames_dropped")
            out = Bundle.empty(self.e, rnd)
        return out


# -- one host's view -------------------------------------------------------


class FabricHost:
    """One host's slice of the fleet: the full-geometry engine with ghost
    lanes, the extract/inject endpoints, the wire codec, counters, spans,
    and (optionally) the owned-lane trajectory chain."""

    def __init__(
        self,
        placement: Placement,
        host: int,
        seed: int = 1,
        shape=None,
        cap: int | None = None,
        schedule=None,
        track_trajectory: bool = False,
        **cfg,
    ):
        if not fabric_enabled():
            raise RuntimeError(
                "cross-host fabric is disabled: set RAFT_TPU_FABRIC=1"
            )
        from raft_tpu.ops.fused import FusedCluster

        self.placement = placement
        self.host = int(host)
        self.cl = FusedCluster(
            placement.n_groups, placement.n_voters, seed=seed, shape=shape, **cfg
        )
        self.v = placement.n_voters
        self.e = int(self.cl.fab.rep.ent_term.shape[-1])
        self.own = placement.own_mask(host)
        if (~self.own).any():
            _mark_ghosts(self.cl, ~self.own, self.v)
        self.counters = HostCounters()
        # pre-seed the full fabric family so snapshots export a stable
        # schema (a zero drop counter is a signal, not a missing series)
        from raft_tpu.metrics.host import FABRIC_COUNTERS

        for name in FABRIC_COUNTERS:
            self.counters.inc(name, 0)
        self.spans = SpanRecorder()
        self.extractor = FabricExtractor(placement, host, cap)
        self.injector = FabricInjector(placement, host, cap)
        self.wire = FabricWire(self.v, self.e, counters=self.counters)
        self.gate = WireGate(schedule, self.counters, self.e)
        self.peers = placement.peers(host)
        self.trajectory = TrajectoryDigest() if track_trajectory else None
        self._pending: list = []
        self.round = 0

    # -- one lockstep round ------------------------------------------------

    def step(self, ops_spec=None, **run_kw) -> dict:
        """Inject pending -> run(1) -> extract -> gate + encode. Returns
        {peer: frame_bytes}, ALWAYS one frame per peer (empty frames are
        the round barrier). ops_spec is the global {field: {lane: value}}
        dict, filtered to owned lanes here (the mono twin applies it
        whole)."""
        rnd = self.round
        merged = merge_bundles(self._pending, self.e, rnd)
        self._pending = []
        if merged.count:
            fab, injected, dropped = self.injector(self.cl.fab, merged)
            self.cl.fab = fab
            self.counters.inc("fabric_msgs_injected", injected)
            if dropped:
                self.counters.inc("fabric_injection_drops", dropped)
        ops = None
        if ops_spec:
            kept = _filter_ops_spec(ops_spec, self.own)
            if kept:
                ops = self.cl.ops(**kept)
        self.cl.run(1, ops=ops, **run_kw)
        fab, bundle, total = self.extractor(self.cl.fab, rnd)
        if bundle is not None:
            self.cl.fab = fab
            self.counters.inc("fabric_msgs_exported", bundle.count)
        self.counters.inc("fabric_msgs_total", int(total))
        parts = split_bundle(bundle, self.placement, self.e)
        frames = {}
        for p in self.peers:
            out = self.gate.outbound(rnd, self.host, p, parts.get(p))
            frame = self.wire.encode(out, rnd)
            if out.count:
                self.spans.spans.append((
                    "fabric_tx", time.perf_counter(), 0.0,
                    dict(round=rnd, peer=p, msgs=out.count,
                         bytes=len(frame), groups=self._groups_of(out)),
                ))
            frames[p] = frame
        if self.trajectory is not None:
            self.trajectory.update(owned_rows(self.cl, self.own))
        self.round += 1
        return frames

    def receive(self, frame: bytes, peer: int = -1) -> None:
        """Decoded frames become next round's injections (bridge IMPORT)."""
        b = self.wire.decode(frame)
        if b.count:
            self._pending.append(b)
            self.spans.spans.append((
                "fabric_rx", time.perf_counter(), 0.0,
                dict(round=b.round, peer=peer, msgs=b.count,
                     bytes=len(frame), groups=self._groups_of(b)),
            ))

    def _groups_of(self, bundle: Bundle) -> tuple:
        vv = self.v * self.v
        return tuple(sorted({int(c) // vv for c in bundle.cell}))

    # -- observability -----------------------------------------------------

    def metrics_snapshot(self) -> dict:
        """Fabric counters folded with the engine's device snapshot (when
        RAFT_TPU_METRICS=1), mirrored process-wide for /metrics exports."""
        from raft_tpu.metrics.host import merge_snapshots, record_fabric_stats

        record_fabric_stats(self.counters.counts)
        snaps = [self.counters.snapshot()]
        eng = self.cl.metrics_snapshot()
        if eng is not None:
            snaps.append(eng)
        return merge_snapshots(snaps)


# -- in-process lockstep coordinator ---------------------------------------


class LockstepFabric:
    """All hosts of a placement in one process, stepped in lockstep —
    the unit-test / chaos-probe driver (no IPC, same protocol and same
    WireGate semantics as the spawned workers)."""

    def __init__(self, placement: Placement, seed: int = 1, **host_kw):
        self.placement = placement
        self.hosts = [
            FabricHost(placement, h, seed=seed, **host_kw)
            for h in range(placement.n_hosts)
        ]
        self.round = 0

    def run(self, rounds: int = 1, ops_spec=None, **run_kw) -> "LockstepFabric":
        for i in range(rounds):
            spec = ops_spec if i == 0 else None
            frames = {fh.host: fh.step(spec, **run_kw) for fh in self.hosts}
            for src, out in frames.items():
                for dst, frame in out.items():
                    self.hosts[dst].receive(frame, peer=src)
            self.round += 1
        return self

    # -- stitched inspection ----------------------------------------------

    def state_columns(self, *names) -> dict:
        """Global columns stitched from each host's owned lanes."""
        out = {}
        for name in names:
            full = None
            for fh in self.hosts:
                col = fh.cl.state_columns(name)[name]
                if full is None:
                    full = np.zeros_like(col)
                full[fh.own] = col[fh.own]
            out[name] = full
        return out

    def leader_lanes(self) -> np.ndarray:
        from raft_tpu.types import StateType

        st = self.state_columns("state")["state"]
        return np.nonzero(st == int(StateType.LEADER))[0]

    def digest(self) -> str:
        """Stitched digest of the CURRENT state (end-state oracle)."""
        h = hashlib.sha256()
        parts = [(fh.own, owned_rows(fh.cl, fh.own)) for fh in self.hosts]
        n = self.placement.n_lanes
        for i in range(len(parts[0][1])):
            sample = parts[0][1][i]
            full = np.zeros((n,) + sample.shape[1:], sample.dtype)
            for own, rows in parts:
                full[own] = rows[i]
            h.update(np.ascontiguousarray(full).tobytes())
        return h.hexdigest()

    def fleet_trajectory(self) -> str:
        """fleet_digest over the hosts' chained trajectories (needs
        track_trajectory=True)."""
        return fleet_digest([fh.trajectory.hex() for fh in self.hosts])

    def metrics_snapshot(self) -> dict:
        from raft_tpu.metrics.host import merge_snapshots

        return merge_snapshots([fh.metrics_snapshot() for fh in self.hosts])

    def check_no_errors(self) -> None:
        for fh in self.hosts:
            fh.cl.check_no_errors()


# -- multiprocess launcher -------------------------------------------------


def _fabric_worker(host_id: int, placement: Placement, conns: dict, result, cfg: dict):
    """One spawned host process: lockstep rounds against pipe peers. The
    blocking recv per (peer, round) IS the round barrier — every peer
    sends exactly one frame per round, empty or not."""
    try:
        fh = FabricHost(
            placement,
            host_id,
            seed=cfg["seed"],
            cap=cfg.get("cap"),
            schedule=cfg.get("schedule"),
            track_trajectory=True,
            **cfg.get("cluster_cfg") or {},
        )
        for r in range(cfg["rounds"]):
            spec = cfg.get("ops_spec") if r == 0 else None
            frames = fh.step(spec, **cfg.get("run_kw") or {})
            for p, frame in frames.items():
                send_frame(conns[p], frame)
            for p in fh.peers:
                fh.receive(recv_frame(conns[p]), peer=p)
        own = fh.own
        leaders = [int(x) for x in fh.cl.leader_lanes() if own[int(x)]]
        cols = fh.cl.state_columns("state", "term", "committed", "lead")
        result.put(
            dict(
                host=host_id,
                own=own,
                rows=owned_rows(fh.cl, own),
                digest=fh.trajectory.hex(),
                counters=dict(fh.counters.counts),
                leaders=leaders,
                columns={k: v for k, v in cols.items()},
                n_spans=len(fh.spans.spans),
            )
        )
    except Exception:
        result.put(dict(host=host_id, error=traceback.format_exc()))


def run_fabric_workers(
    placement: Placement,
    *,
    rounds: int,
    seed: int = 1,
    ops_spec=None,
    run_kw=None,
    schedule=None,
    cap=None,
    cluster_cfg=None,
    timeout: float = 600.0,
) -> list:
    """Fork one worker process per host (spawn context — children inherit
    the parent's RAFT_TPU_* env), wire pairwise pipes between fabric
    peers, run `rounds` lockstep rounds, and return the per-host result
    dicts (own mask, owned state rows, trajectory digest, counters,
    leaders, state columns) in host order."""
    if not fabric_enabled():
        raise RuntimeError("cross-host fabric is disabled: set RAFT_TPU_FABRIC=1")
    import multiprocessing as mp

    ctx = mp.get_context("spawn")
    n = placement.n_hosts
    conns: dict = {h: {} for h in range(n)}
    for a in range(n):
        for b in placement.peers(a):
            if b > a:
                ca, cb = ctx.Pipe()
                conns[a][b] = ca
                conns[b][a] = cb
    q = ctx.Queue()
    cfg = dict(
        seed=seed,
        rounds=rounds,
        ops_spec=ops_spec,
        run_kw=run_kw,
        schedule=schedule,
        cap=cap,
        cluster_cfg=cluster_cfg,
    )
    procs = [
        ctx.Process(
            target=_fabric_worker,
            args=(h, placement, conns[h], q, cfg),
            daemon=True,
        )
        for h in range(n)
    ]
    for p in procs:
        p.start()
    results: dict = {}
    deadline = time.time() + timeout
    try:
        while len(results) < n:
            remaining = deadline - time.time()
            if remaining <= 0:
                raise TimeoutError(
                    f"fabric workers timed out; got {sorted(results)} of {n}"
                )
            r = q.get(timeout=remaining)
            if "error" in r:
                raise RuntimeError(
                    f"fabric worker {r['host']} failed:\n{r['error']}"
                )
            results[r["host"]] = r
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return [results[h] for h in range(n)]


def workers_fleet_digest(results) -> str:
    """fleet_digest over worker results (host order)."""
    return fleet_digest([r["digest"] for r in results])


def stitched_columns(results, n_lanes: int) -> dict:
    """Global state columns stitched from worker results."""
    out: dict = {}
    for r in results:
        own = np.asarray(r["own"])
        for name, col in r["columns"].items():
            if name not in out:
                out[name] = np.zeros_like(col)
            out[name][own] = col[own]
    return out
