"""Ingress injection: decoded frames -> fabric cells at the round boundary.

The mirror of extract.py, with bridge.py's IMPORT convention: a message
from remote member R (a ghost lane here) to local member L lands in
fabric cell [lane(R), slot(L)] — exactly where R's own outbox write
would sit — so the next round's route_fabric transpose delivers it to L
like any resident traffic. Injection happens between dispatches, before
the next run, which reproduces the monolithic emit-round-r /
consume-round-r+1 latency exactly (the wire exchange IS the round
boundary in the lockstep driver). Under RAFT_TPU_FABRIC_SKEW=D the
driver holds a decoded bundle in its staging map until D+1 rounds after
its emit tag, so injection models a fixed D-round wire latency instead —
same scatter, later round boundary (driver.py's skew contract).

Host-side validation happens in numpy before the jit: a row whose dst
lane is not owned here, or whose src lane is not a ghost here, or whose
chan/cell is out of range, is dropped and counted
(fabric_injection_drops) — a malformed or misrouted frame can never
scribble on resident lanes. Valid rows are padded to the static
capacity so every round reuses ONE jit signature.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.fabric import fabric_cap
from raft_tpu.fabric.placement import CHANNELS
from raft_tpu.fabric.extract import Bundle, ENT_FIELDS, SCALAR_FIELDS

I32 = jnp.int32


def inject_bundle(fab, chan, cell, valid, cols):
    """Scatter validated wire rows into the fabric carry.

    fab    the pre-round Fabric carry (slim/diet dtypes preserved)
    chan   [cap] i32 channel index (placement.CHANNELS order)
    cell   [cap] i32 flat fabric cell src_lane * V + dst_slot
    valid  [cap] bool (padding + host-side-dropped rows are False)
    cols   superset columns: [cap] i32 scalars, [cap, E] i32 ent_*

    Each channel scatters exactly its own dataclass fields from the
    superset (the extract gather's symmetric inverse); invalid rows
    scatter to the out-of-range sentinel and drop.
    """
    n, v = fab.hb.kind.shape
    nv = n * v
    out = {}
    for ci, name in enumerate(CHANNELS):
        c = getattr(fab, name)
        sel = jnp.where((chan == ci) & valid, cell, nv)
        upd = {}
        for f in dataclasses.fields(c):
            x = getattr(c, f.name)
            vals = cols[f.name].astype(x.dtype)
            if f.name in ENT_FIELDS:
                flat = x.reshape(nv, -1).at[sel].set(vals, mode="drop")
            else:
                flat = x.reshape(nv).at[sel].set(vals, mode="drop")
            upd[f.name] = flat.reshape(x.shape)
        out[name] = dataclasses.replace(c, **upd)
    return dataclasses.replace(fab, **out)


_inject_jit = jax.jit(inject_bundle)


class FabricInjector:
    """Per-host inject endpoint: validates decoded bundles in numpy, pads
    to the static capacity, scatters on device. Returns the drop count so
    the driver can feed fabric_injection_drops."""

    def __init__(self, placement, host: int, cap: int | None = None):
        self.placement = placement
        self.host = int(host)
        self.n_in = placement.n_in_cells(host)
        # lossless bound, mirroring the extract side: one message per
        # channel per inbound cell per round
        self.cap = int(
            cap if cap is not None else (fabric_cap() or len(CHANNELS) * self.n_in)
        )
        self._own = placement.own_mask(host)
        self._in_cells = placement.in_cells(host).reshape(-1)

    def warmup(self, fab) -> None:
        """Compile the scatter program before the first real injection.

        Under RAFT_TPU_FABRIC_SKEW=D the first non-empty bundle lands at
        round >= D+1 — inside any steady-state timing window — and a
        mid-run XLA compile (~0.5 s) there dwarfs the per-round cost the
        pipeline is trying to hide. The warmup batch is all-invalid
        (every row scatters to the drop sentinel) and the result is
        discarded, so the carry is untouched.
        """
        e = int(fab.rep.ent_term.shape[-1])
        z = jnp.zeros((self.cap,), jnp.int32)
        cols = {f: z for f in SCALAR_FIELDS}
        cols.update(
            {f: jnp.zeros((self.cap, e), jnp.int32) for f in ENT_FIELDS}
        )
        jax.block_until_ready(
            _inject_jit(fab, z, z, jnp.zeros((self.cap,), jnp.bool_), cols)
        )

    def __call__(self, fab, bundle: Bundle):
        """-> (fab_with_injections, n_injected, n_dropped)."""
        if bundle is None or bundle.count == 0:
            return fab, 0, 0
        k = bundle.count
        if k > self.cap:
            raise RuntimeError(
                f"fabric inject overflow: {k} inbound messages in one round "
                f"> cap {self.cap} (host {self.host}); raise "
                f"RAFT_TPU_FABRIC_CAP"
            )
        chan = bundle.chan.astype(np.int64)
        cell = bundle.cell.astype(np.int64)
        nv = self._in_cells.shape[0]
        ok = (chan >= 0) & (chan < len(CHANNELS)) & (cell >= 0) & (cell < nv)
        # the landing site must be a legitimate inbound cell: src ghost
        # here AND dst owned here (placement.in_cells precomputes that)
        ok &= self._in_cells[np.clip(cell, 0, nv - 1)]
        dropped = int((~ok).sum())
        if dropped == k:
            return fab, 0, dropped

        def pad(x, fill=0):
            full = np.full((self.cap,) + x.shape[1:], fill, x.dtype)
            full[:k] = x
            return jnp.asarray(full)

        valid = pad(ok.astype(np.bool_))
        cols = {
            f: pad(bundle.cols[f]) for f in SCALAR_FIELDS + ENT_FIELDS
        }
        fab = _inject_jit(
            fab, pad(chan.astype(np.int32)), pad(cell.astype(np.int32)), valid, cols
        )
        return fab, k - dropped, dropped
