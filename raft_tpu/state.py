"""Device-resident batched raft state.

One *lane* == one raft node (one member of one raft group), mirroring the
reference `raft` struct (reference: raft.go:338-430) flattened into arrays
batched over the lane axis N, per SURVEY §7's state layout:

- `[N]` per-node scalars (term, vote, lead, role, tick counters, ...)
- `[N, V]` per-peer progress/vote lanes (reference: tracker/progress.go:30-98,
  tracker/tracker.go:117-126)
- `[N, V, F]` inflight rings (reference: tracker/inflights.go:28-40)
- `[N, W]` columnar circular log window of (term, type, size) — the merged
  raftLog/unstable/MemoryStorage metadata view (reference: log.go:24-63,
  log_unstable.go:33-50, storage.go:98-120). Entry *payloads* never live on
  device; they are keyed host-side by (group, index, term).

Everything is int32/bool_: TPUs have no fast int64 path and every decision in
the reference log layer reads only Term/Index/size (log.go:109-456).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import (
    DEFAULT_ELECTION_TICK,
    DEFAULT_HEARTBEAT_TICK,
    DEFAULT_MAX_COMMITTED_SIZE_PER_READY,
    DEFAULT_MAX_SIZE_PER_MSG,
    DEFAULT_MAX_UNCOMMITTED_SIZE,
    Shape,
    env_flag,
)
from raft_tpu.types import StateType

I32 = jnp.int32
BOOL = jnp.bool_


def _dc(cls):
    """Register a dataclass whose fields are all pytree data."""
    fields = [f.name for f in dataclasses.fields(cls)]
    return jax.tree_util.register_dataclass(cls, data_fields=fields, meta_fields=[])


@_dc
@dataclasses.dataclass(frozen=True)
class LaneConfig:
    """Per-lane dynamic tunables — the batched `Config` (reference:
    raft.go:124-286). Device arrays so heterogeneous groups share one compiled
    program."""

    election_tick: Any  # [N] i32
    heartbeat_tick: Any  # [N] i32
    max_size_per_msg: Any  # [N] i32, bytes per MsgApp (raft.go:188)
    max_uncommitted_size: Any  # [N] i32 (raft.go:200-204)
    max_committed_size_per_ready: Any  # [N] i32 (raft.go:193-199)
    max_inflight: Any  # [N] i32 in-flight MsgApp count cap (raft.go:211-215)
    max_inflight_bytes: Any  # [N] i32 (raft.go:216-220)
    check_quorum: Any  # [N] bool (raft.go:221-225)
    pre_vote: Any  # [N] bool (raft.go:226-229)
    read_only_lease_based: Any  # [N] bool (raft.go:230-240)
    disable_proposal_forwarding: Any  # [N] bool (raft.go:257-265)
    step_down_on_removal: Any  # [N] bool (raft.go:272-276)
    disable_conf_change_validation: Any  # [N] bool (raft.go:266-271)


@_dc
@dataclasses.dataclass(frozen=True)
class RaftState:
    """The complete batched state machine. All arrays leading dim N."""

    # --- identity & role (reference: raft.go:338-430) ---
    id: Any  # [N] i32: this node's raft id within its group
    term: Any  # [N] i32
    vote: Any  # [N] i32
    state: Any  # [N] i32 StateType
    lead: Any  # [N] i32
    lead_transferee: Any  # [N] i32 (raft.go:398)
    is_learner: Any  # [N] bool (raft.go:356)
    pending_conf_index: Any  # [N] i32 (raft.go:390-394)
    uncommitted_size: Any  # [N] i32 payload bytes (raft.go:396, 2033-2047)

    # --- tick machinery (reference: raft.go:400-421, 823-862, 1984-1990) ---
    election_elapsed: Any  # [N] i32
    heartbeat_elapsed: Any  # [N] i32
    randomized_election_timeout: Any  # [N] i32
    rng: Any  # [N] u32 per-lane LCG state (replaces lockedRand, raft.go:89-102)

    # --- log window (reference: log.go:24-63 + log_unstable.go + storage.go) ---
    # Entry index i occupies slot i & (W-1) when snap_index < i <= last.
    log_term: Any  # [N, W] i32
    log_type: Any  # [N, W] i32 EntryType
    log_bytes: Any  # [N, W] i32 payload size
    last: Any  # [N] i32 lastIndex
    stabled: Any  # [N] i32 highest index durably persisted (unstable.offset-1)
    committed: Any  # [N] i32
    applying: Any  # [N] i32 (log.go:45-57)
    applied: Any  # [N] i32
    snap_index: Any  # [N] i32 compaction point: firstIndex = snap_index+1
    snap_term: Any  # [N] i32
    # In-flight incoming snapshot (unstable.snapshot, log_unstable.go:38-40):
    pending_snap_index: Any  # [N] i32 (0 = none)
    pending_snap_term: Any  # [N] i32
    # The application's latest snapshot — what Storage.Snapshot() would
    # return (reference: storage.go:79-84). May run ahead of the compaction
    # point; it is what leaders send in MsgSnap (raft.go:636-649).
    avail_snap_index: Any  # [N] i32 (0 = none)
    avail_snap_term: Any  # [N] i32
    # Storage.Snapshot() deferral (reference: storage.go:36-38
    # ErrSnapshotTemporarilyUnavailable): while set, the leader skips the
    # MsgSnap fallback without erroring and retries later (raft.go:625-649)
    snap_unavailable: Any  # [N] bool

    # --- membership (reference: tracker/tracker.go:27-78) ---
    # Slot-major: peer slot j of lane n describes group-member prs_id[n, j].
    # Slot 0 is always the lane's own id when it is part of the config.
    prs_id: Any  # [N, V] i32 (0 = empty slot)
    voters_in: Any  # [N, V] bool — incoming (main) voter set
    voters_out: Any  # [N, V] bool — outgoing set when in joint consensus
    learners: Any  # [N, V] bool
    learners_next: Any  # [N, V] bool
    auto_leave: Any  # [N] bool

    # --- per-peer progress (reference: tracker/progress.go:30-98) ---
    pr_match: Any  # [N, V] i32
    pr_next: Any  # [N, V] i32
    pr_state: Any  # [N, V] i32 ProgressState
    pr_pending_snapshot: Any  # [N, V] i32
    pr_recent_active: Any  # [N, V] bool
    pr_msg_app_flow_paused: Any  # [N, V] bool
    # votes (reference: tracker/tracker.go:121 Votes map)
    votes: Any  # [N, V] i32 VoteState

    # --- inflights ring (reference: tracker/inflights.go:28-40) ---
    infl_index: Any  # [N, V, F] i32
    infl_bytes: Any  # [N, V, F] i32
    infl_start: Any  # [N, V] i32
    infl_count: Any  # [N, V] i32
    infl_total_bytes: Any  # [N, V] i32

    # --- read-only (linearizable read) tracking ---
    # Outstanding ReadOnlySafe requests: the batched readOnly queue
    # (reference: read_only.go:39-43). A slot is live when ro_ctx != 0;
    # ro_acks is the per-voter heartbeat-ack set (read_only.go:68-79).
    ro_ctx: Any  # [N, R] i32 request ctx ticket (0 = free slot)
    ro_from: Any  # [N, R] i32 requester raft id
    ro_index: Any  # [N, R] i32 commit index captured at enqueue
    ro_acks: Any  # [N, R, V] bool
    # FIFO order of the readOnly queue (read_only.go:42 readIndexQueue): a
    # quorum ack for ctx releases every live slot with seq <= its seq (the
    # reference's advance() prefix rule, read_only.go:81-112)
    ro_seq: Any  # [N, R] i32 enqueue sequence (valid where ro_ctx != 0)
    ro_next_seq: Any  # [N] i32 monotonic counter (starts at 1)
    # MsgReadIndex arriving before the leader commits in its term, postponed
    # until the first commit (raft.go:1313-1317 pendingReadIndexMessages;
    # bounded at R here — overflow drops and the client retries)
    pri_ctx: Any  # [N, R] i32 (0 = free slot)
    pri_from: Any  # [N, R] i32
    # Released ReadStates awaiting host pickup (reference: raft.go:371
    # readStates slice, drained by Ready).
    rs_ctx: Any  # [N, R] i32
    rs_index: Any  # [N, R] i32
    rs_count: Any  # [N] i32

    # Where the reference panics on broken invariants (e.g. log.go:319-324,
    # log.go:135-137), a lockstep tensor program can't: violations set a bit
    # here and the offending update is clamped to a no-op. Tests and the host
    # runtime assert this stays zero (the batched analog of `go test -race`
    # + panic: SURVEY §5 race-detection parity).
    error_bits: Any  # [N] i32

    cfg: LaneConfig

    # --- leader-lease plane (RAFT_TPU_LEASE, ops/lease.py) ---
    # Optional columns: None (and therefore absent from every jaxpr and
    # every carry byte count) unless the lease plane is enabled at
    # construction. lease_left is a COUNTDOWN in rounds, not an absolute
    # round — the carry has no round counter and a countdown needs no
    # rebase under diet-v2 (packs as uint16, bounded by election_tick).
    lease_left: Any = None  # [N] rounds of lease remaining (0 = none)
    lease_epoch: Any = None  # [N] grant generation (wraps at 2^15)
    lease_skew: Any = None  # [N] skipped ticks observed while leased
    lease_grants: Any = None  # [N] monotone event counters (host sums)
    lease_renewals: Any = None  # [N]
    lease_revocations: Any = None  # [N]
    lease_skew_revocations: Any = None  # [N]

    # Convenience views ----------------------------------------------------
    @property
    def first_index(self):
        """reference: log.go firstIndex == snapshot index + 1."""
        return self.snap_index + 1

    def slot(self, index):
        w = self.log_term.shape[-1]
        return index & (w - 1)


# --------------------------------------------------------------------------
# Carry diet: narrow on-HBM dtypes for enum/counter fields.
#
# All round *compute* stays int32 (TPU-native); these narrow types exist only
# at storage boundaries — the lax.scan carry between fused rounds and the
# resident state of idle blocks — where HBM footprint, not ALU width, is what
# bounds how many groups fit one chip (BASELINE config 5: 1M resident
# groups). Values round-trip exactly: every slimmed field is a small enum or
# a bounded counter (bounds asserted in make_lane_config / Shape).
#
# reference scaling intent: tracker/inflights.go:83-85 sizes for "thousands
# of Raft groups per process"; this is that frugality taken to tensor form.

STATE_SLIM = {
    "state": jnp.int8,  # StateType 0..3
    "votes": jnp.int8,  # VoteState 0..2
    "pr_state": jnp.int8,  # ProgressState 0..2
    "log_type": jnp.int8,  # EntryType 0..2
    "election_elapsed": jnp.int16,  # < 2*election_tick (<= 2^14 asserted)
    "heartbeat_elapsed": jnp.int16,
    "randomized_election_timeout": jnp.int16,
    "infl_start": jnp.int8,  # < max_inflight (<= 64 asserted in Shape use)
    "infl_count": jnp.int8,
    "rs_count": jnp.int8,  # <= max_read_index
}


def _cast_fields(obj, dtype_map, widen: bool):
    upd = {}
    for f, dt in dtype_map.items():
        x = getattr(obj, f)
        target = jnp.int32 if widen else dt
        if x.dtype != target:
            upd[f] = x.astype(target)
    return dataclasses.replace(obj, **upd) if upd else obj


def slim_state(state: "RaftState") -> "RaftState":
    """Cast the dieted fields to their narrow storage dtypes (idempotent)."""
    return _cast_fields(state, STATE_SLIM, widen=False)


def fat_state(state: "RaftState") -> "RaftState":
    """Restore all dieted fields to int32 for round compute (idempotent)."""
    return _cast_fields(state, STATE_SLIM, widen=True)


# --------------------------------------------------------------------------
# Byte diet v2 (RAFT_TPU_DIET): packed bitsets + rebased narrow indices.
#
# A second, opt-in storage boundary beside slim/fat: `pack_state` narrows
# the slim-canonical layout further for the resident carry, `unpack_state`
# restores it exactly. Both are idempotent; `unpack_state(pack_state(x))`
# is bit-identical to `slim_state(x)` for every in-range value, so diet-on
# and diet-off runs walk the same trajectory (benches/diet_ab.py holds the
# digests together).
#
# - every [N, V] bool mask (and the [N, R, V] ro_acks) packs into one
#   bitset word per lane — the smallest unsigned width that holds V bits,
#   so a 3-voter group pays 1 byte, not 4 (Shape validates V <= 32);
# - index-valued columns store as uint16 ABSOLUTE values in the already-
#   rebased index space (every one of them is shifted by ops/log.py
#   rebase_indexes, so "offset from the per-lane base" is the value
#   itself once FusedCluster's auto-rebase keeps max(last) under
#   DIET_REBASE_AT). Term columns ride the same width: terms count
#   elections, not entries, and the overflow check below flags the
#   pathological case rather than ever wrapping silently;
# - small-id columns (canonical ids 1..V) store as int8;
# - log_bytes stores as int16 under Shape.max_entry_bytes.
#
# Out-of-range values at a pack boundary CLAMP and set ERR_DIET_OVERFLOW
# in error_bits — never a silent wrap; tests/chaos soaks assert the bit
# stays zero (ops/log.py re-exports the flag beside its ERR_* family).

ERR_DIET_OVERFLOW = 64
# paged entry log (ops/paged.py): page pool ran out during page_out — the
# overflowing lane's paged tail is clamped (dropped pages read back as
# zero/absent entries), never silently wrapped. Same contract shape as
# ERR_DIET_OVERFLOW: error_bits itself is never packed, so the flag is
# representable under every storage mode.
ERR_PAGE_EXHAUSTED = 128

# inclusive value range per packed storage dtype
_DIET_RANGE = {
    jnp.uint16: (0, (1 << 16) - 1),
    jnp.int16: (-(1 << 15), (1 << 15) - 1),
    jnp.int8: (-128, 127),
}

# rebased index columns + term columns -> uint16 (all index fields here are
# the exact set ops/log.py rebase_indexes shifts)
PACK_U16 = (
    "term", "snap_term", "pending_snap_term", "avail_snap_term", "log_term",
    "last", "stabled", "committed", "applying", "applied",
    "snap_index", "pending_snap_index", "avail_snap_index",
    "pending_conf_index",
    "pr_match", "pr_next", "pr_pending_snapshot",
    "infl_index", "ro_index", "rs_index",
)
# canonical raft ids 1..V (V <= 32) -> int8
PACK_I8 = ("id", "vote", "lead", "lead_transferee", "prs_id", "ro_from", "pri_from")
# entry payload sizes bounded by Shape.max_entry_bytes -> int16
PACK_I16 = ("log_bytes",)
# bool mask columns -> one bitset word per lane along the trailing V axis
PACK_BITSET = (
    "voters_in", "voters_out", "learners", "learners_next",
    "pr_recent_active", "pr_msg_app_flow_paused", "ro_acks",
)
# LaneConfig columns with config-time-validated bounds (make_lane_config)
CFG_PACK = {
    "election_tick": jnp.int16,  # <= 2^14, validated
    "heartbeat_tick": jnp.int16,  # <= 2^14, validated
    "max_inflight": jnp.int8,  # <= 127, validated
}


def diet_enabled() -> bool:
    """Read RAFT_TPU_DIET lazily (default OFF) so tests/benches can toggle
    it per-cluster; like donation_enabled, the value is baked into each
    cluster at construction and the carry layout never flips mid-run."""
    return env_flag("RAFT_TPU_DIET", default=False)


def bitset_dtype(v: int):
    """Smallest unsigned word holding v mask bits (Shape caps v at 32)."""
    if v <= 8:
        return jnp.uint8
    if v <= 16:
        return jnp.uint16
    if v <= 32:
        return jnp.uint32
    raise ValueError(f"bitset packing needs v <= 32, got {v}")


def pack_bits(x, dtype):
    """[..., V] bool -> [...] bitset word (bit j = column j)."""
    v = x.shape[-1]
    w = jnp.left_shift(jnp.uint32(1), jnp.arange(v, dtype=jnp.uint32))
    return jnp.sum(x.astype(jnp.uint32) * w, axis=-1).astype(dtype)


def unpack_bits(x, v: int):
    """[...] bitset word -> [..., V] bool (exact inverse of pack_bits)."""
    b = jnp.right_shift(
        x[..., None].astype(jnp.uint32), jnp.arange(v, dtype=jnp.uint32)
    )
    return (b & jnp.uint32(1)).astype(BOOL)


def is_packed(state: "RaftState") -> bool:
    """Diet-v2 layout detector (static under jit: leaf ndim)."""
    return getattr(state.voters_in, "ndim", 2) == 1


def pack_state(state: "RaftState") -> "RaftState":
    """Slim/fat -> diet-v2 packed storage layout (idempotent). Values
    outside a field's packed range clamp and set ERR_DIET_OVERFLOW —
    flagged, never silently wrapped."""
    if is_packed(state):
        return state
    state = slim_state(state)
    v = state.voters_in.shape[-1]
    bd = bitset_dtype(v)
    ovf = jnp.zeros(state.term.shape, BOOL)
    upd = {}

    def narrow(name, dt):
        nonlocal ovf
        x = getattr(state, name)
        lo, hi = _DIET_RANGE[dt]
        bad = (x < lo) | (x > hi)
        while bad.ndim > 1:
            bad = bad.any(axis=-1)
        ovf = ovf | bad
        upd[name] = jnp.clip(x, lo, hi).astype(dt)

    for f in PACK_U16:
        narrow(f, jnp.uint16)
    for f in PACK_I8:
        narrow(f, jnp.int8)
    for f in PACK_I16:
        narrow(f, jnp.int16)
    for f in PACK_BITSET:
        upd[f] = pack_bits(getattr(state, f), bd)
    if state.lease_left is not None:
        # optional lease-plane columns (RAFT_TPU_LEASE, ops/lease.py):
        # lease_left/lease_skew are bounded by election_tick (<= 2^14,
        # validated) and lease_epoch wraps at 2^15 by construction, so
        # uint16 is exact; the monotone event counters are unbounded and
        # stay int32
        for f in ("lease_left", "lease_epoch", "lease_skew"):
            narrow(f, jnp.uint16)
    upd["error_bits"] = state.error_bits | jnp.where(
        ovf, jnp.int32(ERR_DIET_OVERFLOW), jnp.int32(0)
    )
    # LaneConfig bounds are ValueError-enforced at make_lane_config, so
    # these casts are exact by construction — no overflow check needed
    upd["cfg"] = dataclasses.replace(
        state.cfg,
        **{k: getattr(state.cfg, k).astype(dt) for k, dt in CFG_PACK.items()},
    )
    return dataclasses.replace(state, **upd)


def unpack_state(state: "RaftState") -> "RaftState":
    """Diet-v2 packed -> the exact slim-canonical layout (idempotent).
    Host-visible consumers (WAL, state_columns, confchange) read through
    this so every value surfaces absolute and int32-or-slim, byte-identical
    to a diet-off carry."""
    if not is_packed(state):
        return state
    v = state.prs_id.shape[-1]  # [N, V] survives packing (dtype-only)
    upd = {
        f: getattr(state, f).astype(I32) for f in PACK_U16 + PACK_I8 + PACK_I16
    }
    for f in PACK_BITSET:
        upd[f] = unpack_bits(getattr(state, f), v)
    if state.lease_left is not None:
        for f in ("lease_left", "lease_epoch", "lease_skew"):
            upd[f] = getattr(state, f).astype(I32)
    upd["cfg"] = dataclasses.replace(
        state.cfg,
        **{k: getattr(state.cfg, k).astype(I32) for k in CFG_PACK},
    )
    return dataclasses.replace(state, **upd)


def make_lane_config(shape: Shape, **overrides) -> LaneConfig:
    n = shape.n

    def full(val, dtype=I32):
        return jnp.full((n,), val, dtype=dtype)

    defaults = dict(
        election_tick=full(DEFAULT_ELECTION_TICK),
        heartbeat_tick=full(DEFAULT_HEARTBEAT_TICK),
        max_size_per_msg=full(DEFAULT_MAX_SIZE_PER_MSG),
        max_uncommitted_size=full(DEFAULT_MAX_UNCOMMITTED_SIZE),
        max_committed_size_per_ready=full(DEFAULT_MAX_COMMITTED_SIZE_PER_READY),
        max_inflight=full(shape.max_inflight),
        max_inflight_bytes=full(2**30),
        check_quorum=full(False, BOOL),
        pre_vote=full(False, BOOL),
        read_only_lease_based=full(False, BOOL),
        disable_proposal_forwarding=full(False, BOOL),
        step_down_on_removal=full(False, BOOL),
        disable_conf_change_validation=full(False, BOOL),
    )
    for k, v in overrides.items():
        base = defaults[k]
        defaults[k] = jnp.broadcast_to(jnp.asarray(v, base.dtype), base.shape)
    # the reference's Config.validate (raft.go:288-336): tick values must be
    # positive — a zero tick would make the randomized-timeout draw (% ET)
    # undefined on device
    for k in ("election_tick", "heartbeat_tick"):
        if not bool(np.all(np.asarray(defaults[k]) >= 1)):
            raise ValueError(f"{k} must be >= 1 for every lane")
    # the slim carry stores tick counters as int16 (STATE_SLIM): the
    # randomized timeout is < 2*election_tick and heartbeat_elapsed resets
    # at heartbeat_tick, so 2^14 keeps headroom for both
    for k in ("election_tick", "heartbeat_tick"):
        if not bool(np.all(np.asarray(defaults[k]) <= 1 << 14)):
            raise ValueError(f"{k} must be <= 16384 (int16 carry diet)")
    # the slim carry stores infl_start/infl_count as int8 (STATE_SLIM) and
    # diet-v2 packs max_inflight itself (CFG_PACK): a per-lane override
    # must respect the same bound Shape enforces for its static twin
    mi = np.asarray(defaults["max_inflight"])
    if not bool(np.all((mi >= 1) & (mi <= 127))):
        raise ValueError(
            "max_inflight must be in 1..127 for every lane (int8 carry diet)"
        )
    return LaneConfig(**defaults)


def draw_timeout(rng, election_tick):
    """Randomized election timeout in [ET, 2*ET) from the per-lane PRNG
    (reference: raft.go:1984-1990). High bits only: the LCG's low bits are
    lattice-correlated across lanes. Shared by init_state and the in-kernel
    reset (ops/step.py); election_tick is validated >= 1 at config build."""
    et = election_tick.astype(jnp.uint32)
    return (et + (rng >> jnp.uint32(16)) % et).astype(I32)


def rng_next(rng):
    """One step of the per-lane LCG (Numerical Recipes constants) — the
    batched lockedRand (reference: raft.go:89-102). Shared by the in-kernel
    reset (ops/step.py) and the crash wipe below."""
    return rng * jnp.uint32(1664525) + jnp.uint32(1013904223)


def wipe_volatile(state: RaftState, mask) -> RaftState:
    """Crash-restart the masked lanes IN PLACE: everything the WAL streams
    (runtime/wal.py WalStream.FIELDS — HardState, log metadata, membership,
    cursors) plus the application snapshot origin survives; every volatile
    field resets to the fresh-boot follower defaults of init_state, exactly
    what FusedCluster.restore_from_wal produces when rebuilding a block
    from its delta. Used by the chaos plane (raft_tpu/chaos/) for in-fabric
    lane crashes; `stabled = last` holds because the fused engine persists
    synchronously every round, so a crash loses nothing appended.

    mask: [N] bool. The lane's PRNG advances one step and the randomized
    election timeout redraws, so a restarted lane re-enters the election
    lottery decorrelated from its pre-crash schedule. error_bits are
    deliberately NOT wiped: they are the test oracle, not raft state, and
    a pre-crash invariant violation must stay visible to the soaks."""
    m = mask
    mv = mask[:, None]
    mvf = mask[:, None, None]
    rng2 = jnp.where(m, rng_next(state.rng), state.rng)
    rand2 = jnp.where(
        m,
        draw_timeout(rng2, state.cfg.election_tick).astype(
            state.randomized_election_timeout.dtype
        ),
        state.randomized_election_timeout,
    )
    lease_upd = {}
    if state.lease_left is not None:
        # a crashed lane's lease is gone; lease_epoch deliberately
        # SURVIVES the wipe (a reset epoch could collide with a pre-crash
        # serve-plane snapshot of the same value), and the monotone event
        # counters survive like error_bits — they are the metrics oracle,
        # not raft state
        lease_upd = dict(
            lease_left=jnp.where(m, 0, state.lease_left),
            lease_skew=jnp.where(m, 0, state.lease_skew),
        )
    return dataclasses.replace(
        state,
        **lease_upd,
        state=jnp.where(m, int(StateType.FOLLOWER), state.state),
        lead=jnp.where(m, 0, state.lead),
        lead_transferee=jnp.where(m, 0, state.lead_transferee),
        uncommitted_size=jnp.where(m, 0, state.uncommitted_size),
        election_elapsed=jnp.where(m, 0, state.election_elapsed),
        heartbeat_elapsed=jnp.where(m, 0, state.heartbeat_elapsed),
        randomized_election_timeout=rand2,
        rng=rng2,
        # durability covered everything streamed; applying rejoins applied
        stabled=jnp.where(m, state.last, state.stabled),
        applying=jnp.where(m, state.applied, state.applying),
        pending_snap_index=jnp.where(m, 0, state.pending_snap_index),
        pending_snap_term=jnp.where(m, 0, state.pending_snap_term),
        snap_unavailable=jnp.where(m, False, state.snap_unavailable),
        pr_match=jnp.where(mv, 0, state.pr_match),
        pr_next=jnp.where(mv, 1, state.pr_next),
        pr_state=jnp.where(mv, 0, state.pr_state),
        pr_pending_snapshot=jnp.where(mv, 0, state.pr_pending_snapshot),
        pr_recent_active=jnp.where(mv, False, state.pr_recent_active),
        pr_msg_app_flow_paused=jnp.where(
            mv, False, state.pr_msg_app_flow_paused
        ),
        votes=jnp.where(mv, 0, state.votes),
        infl_index=jnp.where(mvf, 0, state.infl_index),
        infl_bytes=jnp.where(mvf, 0, state.infl_bytes),
        infl_start=jnp.where(mv, 0, state.infl_start),
        infl_count=jnp.where(mv, 0, state.infl_count),
        infl_total_bytes=jnp.where(mv, 0, state.infl_total_bytes),
        ro_ctx=jnp.where(mv, 0, state.ro_ctx),
        ro_from=jnp.where(mv, 0, state.ro_from),
        ro_index=jnp.where(mv, 0, state.ro_index),
        ro_acks=jnp.where(mvf, False, state.ro_acks),
        ro_seq=jnp.where(mv, 0, state.ro_seq),
        ro_next_seq=jnp.where(m, 1, state.ro_next_seq),
        pri_ctx=jnp.where(mv, 0, state.pri_ctx),
        pri_from=jnp.where(mv, 0, state.pri_from),
        rs_ctx=jnp.where(mv, 0, state.rs_ctx),
        rs_index=jnp.where(mv, 0, state.rs_index),
        rs_count=jnp.where(m, 0, state.rs_count),
    )


def init_state(
    shape: Shape,
    ids: np.ndarray,
    peer_ids: np.ndarray,
    peer_is_learner: np.ndarray | None = None,
    seed: int = 1,
    cfg: LaneConfig | None = None,
) -> RaftState:
    """Fresh boot state: every lane a term-0(-becomes-1 on first tick)
    follower with an empty log, mirroring newRaft + becomeFollower(1, None)
    (reference: raft.go:432-477). Bootstrap entries (bootstrap.go:30-80) are
    applied by the host-side bootstrap helper, not here.

    Args:
      ids: [N] this-node raft ids.
      peer_ids: [N, V] group membership per lane, 0-padded, own id included.
      peer_is_learner: [N, V] bool learner mask.
    """
    n, v, w = shape.n, shape.v, shape.w
    f = shape.max_inflight
    r = shape.max_read_index
    ids = np.asarray(ids, np.int32)
    peer_ids = np.asarray(peer_ids, np.int32)
    if peer_ids.shape != (n, v):
        raise ValueError(f"peer_ids must be [{n},{v}], got {peer_ids.shape}")
    if peer_is_learner is None:
        peer_is_learner = np.zeros((n, v), bool)
    present = peer_ids != 0
    voters_in = present & ~peer_is_learner
    self_slot = peer_ids == ids[:, None]
    own_learner = (peer_is_learner & self_slot).any(axis=1)

    # Every zero-initialized field gets its OWN buffer: the fused engine
    # donates the whole state carry (ops/fused.py donation_enabled), and
    # XLA rejects the same buffer appearing in two donated positions
    # ("Attempt to donate the same buffer twice in Execute()").
    def zeros_n():
        return jnp.zeros((n,), I32)

    def zeros_nv():
        return jnp.zeros((n, v), I32)

    # Distinct per-lane streams: lane index scaled by an odd constant so no
    # two lanes collide (a bare +lane collapses adjacent lanes under the |1
    # below), |1 keeps every stream odd.
    rng = np.asarray(
        (
            (seed * 2654435761 + np.arange(n, dtype=np.uint64) * 0x9E3779B9)
            & 0xFFFFFFFF
        )
        | 1,
        np.uint32,
    )

    # First randomized election timeout, drawn from the PER-LANE election
    # tick (reference: newRaft -> becomeFollower -> resetRandomizedElection-
    # Timeout uses Config.ElectionTick, raft.go:476+1984).
    cfg = cfg if cfg is not None else make_lane_config(shape)
    rand_to = draw_timeout(jnp.asarray(rng), cfg.election_tick)

    # leader-lease plane (RAFT_TPU_LEASE, ops/lease.py): like every other
    # optional plane the knob is read at construction; off leaves the
    # fields None — absent from every jaxpr and every carry byte. Each
    # column gets its OWN zero buffer (donation, see zeros_n above).
    from raft_tpu.ops.lease import LEASE_STATE_FIELDS, lease_enabled

    lease_cols = (
        {f: zeros_n() for f in LEASE_STATE_FIELDS} if lease_enabled() else {}
    )

    return RaftState(
        **lease_cols,
        id=jnp.asarray(ids),
        term=zeros_n(),
        vote=zeros_n(),
        state=jnp.full((n,), StateType.FOLLOWER, I32),
        lead=zeros_n(),
        lead_transferee=zeros_n(),
        is_learner=jnp.asarray(own_learner),
        pending_conf_index=zeros_n(),
        uncommitted_size=zeros_n(),
        election_elapsed=zeros_n(),
        heartbeat_elapsed=zeros_n(),
        randomized_election_timeout=jnp.asarray(rand_to),
        rng=jnp.asarray(rng),
        log_term=jnp.zeros((n, w), I32),
        log_type=jnp.zeros((n, w), I32),
        log_bytes=jnp.zeros((n, w), I32),
        last=zeros_n(),
        stabled=zeros_n(),
        committed=zeros_n(),
        applying=zeros_n(),
        applied=zeros_n(),
        snap_index=zeros_n(),
        snap_term=zeros_n(),
        pending_snap_index=zeros_n(),
        pending_snap_term=zeros_n(),
        avail_snap_index=zeros_n(),
        avail_snap_term=zeros_n(),
        snap_unavailable=jnp.zeros((n,), BOOL),
        prs_id=jnp.asarray(peer_ids),
        voters_in=jnp.asarray(voters_in),
        voters_out=jnp.zeros((n, v), BOOL),
        learners=jnp.asarray(peer_is_learner & present),
        learners_next=jnp.zeros((n, v), BOOL),
        auto_leave=jnp.zeros((n,), BOOL),
        pr_match=zeros_nv(),
        pr_next=jnp.ones((n, v), I32),
        pr_state=zeros_nv(),
        pr_pending_snapshot=zeros_nv(),
        pr_recent_active=jnp.zeros((n, v), BOOL),
        pr_msg_app_flow_paused=jnp.zeros((n, v), BOOL),
        votes=zeros_nv(),
        ro_ctx=jnp.zeros((n, r), I32),
        ro_from=jnp.zeros((n, r), I32),
        ro_index=jnp.zeros((n, r), I32),
        ro_acks=jnp.zeros((n, r, v), BOOL),
        ro_seq=jnp.zeros((n, r), I32),
        ro_next_seq=jnp.ones((n,), I32),
        pri_ctx=jnp.zeros((n, r), I32),
        pri_from=jnp.zeros((n, r), I32),
        rs_ctx=jnp.zeros((n, r), I32),
        rs_index=jnp.zeros((n, r), I32),
        rs_count=zeros_n(),
        infl_index=jnp.zeros((n, v, f), I32),
        infl_bytes=jnp.zeros((n, v, f), I32),
        infl_start=zeros_nv(),
        infl_count=zeros_nv(),
        infl_total_bytes=zeros_nv(),
        error_bits=zeros_n(),
        cfg=cfg,
    )
