"""Host-side persisted-log model + restart/recovery.

The reference's `Storage` interface and `MemoryStorage` (reference:
storage.go:46-310) are the durability contract: the application persists
every Ready's entries/HardState/snapshot, and a restarting node rebuilds
itself from `Storage.InitialState` + the stored entries (reference:
node.go:281-289 RestartNode, raft.go:432-477 newRaft, doc.go:46-67).

Here the device holds the algorithmic log (term/type/size columns); this
module supplies the host half of that story:

- `MemoryStorage` — semantics-exact port of the reference's in-memory
  Storage (dummy-entry offset layout, Append truncation cases, Compact,
  ApplySnapshot/CreateSnapshot, InitialState).
- `persist_ready(storage, rd)` — the Ready-side capture helper: apply one
  Ready's durable effects (snapshot, entries, HardState) to a storage, in
  the contract's order (reference: doc.go:75-91).
- `RawNodeBatch.restart_lane` (api/rawnode.py) consumes a MemoryStorage to
  rebuild a lane; this module holds the pure state-derivation helper.
"""

from __future__ import annotations

import dataclasses

from raft_tpu.api.rawnode import Entry, HardState, Ready, Snapshot


class StorageError(Exception):
    pass


ErrCompacted = StorageError("requested index is unavailable due to compaction")
ErrUnavailable = StorageError("requested entry at index is unavailable")
ErrSnapOutOfDate = StorageError("requested index is older than the existing snapshot")


class MemoryStorage:
    """reference: storage.go:98-310. `ents[0]` is the dummy entry holding
    the compaction point (snapshot index/term); real entries follow."""

    def __init__(self):
        self.hard_state = HardState()
        self.snapshot_obj = Snapshot()
        self.ents: list[Entry] = [Entry()]  # dummy @ index 0 term 0

    # -- Storage interface (reference: storage.go:46-90) -------------------

    def initial_state(self) -> tuple[HardState, Snapshot]:
        """(HardState, ConfState-carrier): the ConfState lives on the
        snapshot metadata exactly like the reference (storage.go:121-124)."""
        return self.hard_state, self.snapshot_obj

    def first_index(self) -> int:
        return self.ents[0].index + 1

    def last_index(self) -> int:
        return self.ents[0].index + len(self.ents) - 1

    def term(self, i: int) -> int:
        offset = self.ents[0].index
        if i < offset:
            raise ErrCompacted
        if i - offset >= len(self.ents):
            raise ErrUnavailable
        return self.ents[i - offset].term

    def entries(self, lo: int, hi: int) -> list[Entry]:
        offset = self.ents[0].index
        if lo <= offset:
            raise ErrCompacted
        if hi > self.last_index() + 1:
            raise StorageError(
                f"entries' hi({hi}) is out of bound lastindex({self.last_index()})"
            )
        if len(self.ents) == 1:
            raise ErrUnavailable
        return list(self.ents[lo - offset : hi - offset])

    def snapshot(self) -> Snapshot:
        return self.snapshot_obj

    # -- mutation (reference: storage.go:127-310) --------------------------

    def set_hard_state(self, st: HardState):
        self.hard_state = st

    def apply_snapshot(self, snap: Snapshot):
        if self.snapshot_obj.index >= snap.index:
            raise ErrSnapOutOfDate
        self.snapshot_obj = snap
        self.ents = [Entry(term=snap.term, index=snap.index)]

    def create_snapshot(self, i: int, conf_state=None, data: bytes = b"") -> Snapshot:
        """reference: storage.go:227-249. conf_state: a Snapshot-like or
        ConfState-like carrying voters/learners/... to stamp on the meta."""
        if i <= self.snapshot_obj.index:
            raise ErrSnapOutOfDate
        offset = self.ents[0].index
        if i > self.last_index():
            raise StorageError(
                f"snapshot {i} is out of bound lastindex({self.last_index()})"
            )
        s = self.snapshot_obj
        kw = dict(
            index=i,
            term=self.ents[i - offset].term,
            data=data,
            voters=s.voters,
            learners=s.learners,
            voters_outgoing=s.voters_outgoing,
            learners_next=s.learners_next,
            auto_leave=s.auto_leave,
        )
        if conf_state is not None:
            kw.update(
                voters=tuple(conf_state.voters),
                learners=tuple(conf_state.learners),
                voters_outgoing=tuple(getattr(conf_state, "voters_outgoing", ())),
                learners_next=tuple(getattr(conf_state, "learners_next", ())),
                auto_leave=bool(getattr(conf_state, "auto_leave", False)),
            )
        self.snapshot_obj = Snapshot(**kw)
        return self.snapshot_obj

    def compact(self, compact_index: int):
        offset = self.ents[0].index
        if compact_index <= offset:
            raise ErrCompacted
        if compact_index > self.last_index():
            raise StorageError(
                f"compact {compact_index} is out of bound "
                f"lastindex({self.last_index()})"
            )
        i = compact_index - offset
        head = Entry(term=self.ents[i].term, index=self.ents[i].index)
        self.ents = [head] + self.ents[i + 1 :]

    def append(self, entries: list[Entry]):
        """reference: storage.go:277-310 — the 3-case truncation."""
        if not entries:
            return
        first = self.first_index()
        last = entries[0].index + len(entries) - 1
        if last < first:
            return  # entirely compacted away
        if first > entries[0].index:
            entries = entries[first - entries[0].index :]
        offset = entries[0].index - self.ents[0].index
        if len(self.ents) > offset:
            self.ents = self.ents[:offset] + list(entries)
        elif len(self.ents) == offset:
            self.ents = self.ents + list(entries)
        else:
            raise StorageError(
                f"missing log entry [last: {self.last_index()}, "
                f"append at: {entries[0].index}]"
            )


def persist_ready(storage: MemoryStorage, rd: Ready):
    """Apply one Ready's durable effects to `storage` — what the reference
    application loop does between Ready and Advance (reference: doc.go:75-91;
    snapshot first, then entries, then HardState — the MustSync contract)."""
    if rd.snapshot is not None and rd.snapshot.index:
        if storage.snapshot_obj.index < rd.snapshot.index:
            storage.apply_snapshot(rd.snapshot)
    if rd.entries:
        storage.append([dataclasses.replace(e) for e in rd.entries])
    if rd.hard_state is not None and not rd.hard_state.is_empty():
        storage.set_hard_state(dataclasses.replace(rd.hard_state))
