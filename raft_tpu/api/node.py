"""Threaded event-loop Node API — the reference's goroutine/channel layer
(reference: node.go:132-243 Node interface, 271-289 StartNode, 343-454 run).

One Python thread per `NodeHost` owns the (thread-unsafe) `RawNodeBatch`
exactly like the reference's `node.run()` goroutine owns the RawNode; every
interaction crosses a queue, mirroring the reference's channel set
(propc/recvc/tickc/readyc/advancec/confc, node.go:297-310). All lanes of the
batch share one loop thread — the batched analog of "multinode which can host
multiple raft groups" (reference: raft.go:244-246).

The app-facing contract is the reference's (doc.go:69-145): take a Ready,
persist + send + apply, then Advance. `Node.ready()` blocks like `<-n.Ready()`.
"""

from __future__ import annotations

import dataclasses
import queue
import threading
from typing import Callable

from raft_tpu.api.rawnode import Message, RawNodeBatch, Ready
from raft_tpu.types import (
    LOCAL_APPEND_THREAD,
    LOCAL_APPLY_THREAD,
    LOCAL_MSGS,
    MessageType as MT,
)
from raft_tpu.utils.profiling import StepStats


class ErrStopped(Exception):
    """The node host was stopped while an operation waited (reference:
    node.go:36 ErrStopped, returned by every channel op racing `n.done`)."""


class ErrCanceled(Exception):
    """The caller's cancellation (or deadline) fired while the op waited —
    the context-cancellation arm of the reference's blocking calls
    (node.go:502-545 stepWaitOption select on ctx.Done()). Cancellation
    observed BEFORE the loop picks the op up guarantees it is skipped;
    cancellation racing the loop's execution may still see the op applied —
    exactly the reference's semantics, where a proposal already handed to
    the raft goroutine proceeds even as the caller returns ctx.Err()."""


# op lifecycle: PENDING -> STARTED (loop wins) xor CANCELED (waiter wins).
# The transition is taken under `lock`, making the reference's atomic select
# between the channel send and ctx.Done() (node.go:502-545) a real guarantee:
# a caller that observes CANCELED knows the loop will never execute the op.
_PENDING, _STARTED, _CANCELED = 0, 1, 2


@dataclasses.dataclass
class _Op:
    kind: str
    lane: int
    payload: object = None
    done: threading.Event | None = None
    result: object = None
    error: Exception | None = None
    # cancellation (the ctx.Done() analog): raced against execution via the
    # locked `state` transition; a canceled op is skipped, never half-applied
    cancel: threading.Event | None = None
    state: int = _PENDING
    lock: threading.Lock = dataclasses.field(default_factory=threading.Lock)

    def try_start(self) -> bool:
        """Loop side: claim the op for execution. False if already canceled."""
        with self.lock:
            if self.state == _CANCELED:
                return False
            self.state = _STARTED
            return True

    def try_cancel(self) -> bool:
        """Waiter side: claim cancellation. False if the loop already won."""
        with self.lock:
            if self.state == _STARTED:
                return False
            self.state = _CANCELED
            return True


class NodeHost:
    """Owns the batch + loop thread; hands out per-lane `Node` views."""

    def __init__(self, batch: RawNodeBatch):
        self.batch = batch
        # per-op-kind wall timings on the loop thread (the reference's
        # callStats analog): step_<kind>_count / step_<kind>_micros via
        # stats.snapshot(), registerable with metrics.host.MetricsRegistry
        self.stats = StepStats()
        self._ops: queue.Queue[_Op] = queue.Queue()
        self._ready_q: list[queue.Queue[Ready]] = [
            queue.Queue(maxsize=1) for _ in range(batch.shape.n)
        ]
        self._advance_pending = [False] * batch.shape.n
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def node(self, lane: int) -> "Node":
        return Node(self, lane)

    def stop(self):
        self._stop.set()
        self._ops.put(_Op("noop", 0))
        self._thread.join(timeout=10)

    # -- loop (reference: node.go:343-454) ---------------------------------

    def _run(self):
        while not self._stop.is_set():
            try:
                op = self._ops.get(timeout=0.01)
            except queue.Empty:
                op = None
            if op is not None:
                self._handle(op)
            # surface Readys for lanes that want them (readyc select arm);
            # ready_lanes is the batched egress mask — ONE device dispatch
            # for all lanes instead of a scalar has_ready poll per lane
            with self.stats.timed("ready_poll"):
                lanes = self.batch.ready_lanes()
            for lane in lanes:
                if self._advance_pending[lane]:
                    continue
                if not self._ready_q[lane].empty():
                    continue
                with self.stats.timed("ready_build"):
                    rd = self.batch.ready(lane)
                self._advance_pending[lane] = True
                self._ready_q[lane].put(rd)

    def _handle(self, op: _Op):
        b = self.batch
        # a cancel event observed set before execution claims the CANCELED
        # transition on the waiter's behalf (the waiter may still be inside
        # its poll interval); then the PENDING->STARTED claim races any
        # concurrent try_cancel atomically. Either way: the reference's
        # select never picks the channel send once ctx.Done() fired — a
        # skipped message is not stepped at all.
        canceled = (
            op.cancel is not None and op.cancel.is_set() and op.try_cancel()
        )
        if canceled or not op.try_start():
            op.error = ErrCanceled()
            if op.done is not None:
                op.done.set()
            return
        try:
            with self.stats.timed(op.kind):
                self._execute(op, b)
        except Exception as e:  # surface to caller when waiting
            op.error = e
        finally:
            if op.done is not None:
                op.done.set()

    def _execute(self, op: _Op, b: RawNodeBatch):
        if op.kind == "tick":
            b.tick(op.lane)
        elif op.kind == "propose":
            b.propose(op.lane, op.payload)
        elif op.kind == "propose_cc":
            data, v2 = op.payload
            b.propose_conf_change(op.lane, data, v2=v2)
        elif op.kind == "step":
            b.step(op.lane, op.payload)
        elif op.kind == "advance":
            b.advance(op.lane)
            self._advance_pending[op.lane] = False
        elif op.kind == "campaign":
            b.campaign(op.lane)
        elif op.kind == "apply_cc":
            op.result = b.apply_conf_change(op.lane, op.payload)
        elif op.kind == "transfer":
            b.transfer_leadership(op.lane, op.payload)
        elif op.kind == "read_index":
            b.read_index(op.lane, op.payload)
        elif op.kind == "report_unreachable":
            b.report_unreachable(op.lane, op.payload)
        elif op.kind == "report_snapshot":
            peer, ok = op.payload
            b.report_snapshot(op.lane, peer, ok)
        elif op.kind == "status":
            op.result = b.status(op.lane)
        elif op.kind == "compact":
            idx, data = op.payload
            b.compact(op.lane, idx, data)

    def metrics_snapshot(self) -> dict:
        """Loop-thread op timings in the snapshot schema (no histogram) —
        register with a MetricsRegistry next to the engine/serve planes."""
        return self.stats.snapshot()

    def _submit(
        self, kind, lane, payload=None, wait=False, timeout=None, cancel=None
    ):
        """wait=True blocks like the reference's stepWait (node.go:502-545):
        `timeout` (seconds) is the ctx-deadline analog, `cancel` (a
        threading.Event) the ctx-cancellation analog. Ops whose
        cancellation fires before the loop reaches them are never applied."""
        # a deadline needs its own cancel event so the op is skipped (not
        # executed late) once the caller has given up on it
        if wait and timeout is not None and cancel is None:
            cancel = threading.Event()
        # the cancel event is honored even for fire-and-forget submissions:
        # the loop checks it before claiming the op (the documented
        # "canceled before the loop reaches it => never applied" guarantee
        # does not depend on anyone waiting)
        op = _Op(
            kind, lane, payload,
            threading.Event() if wait else None,
            cancel=cancel,
        )
        self._ops.put(op)
        if wait:
            # default: no deadline — first XLA compiles can take minutes;
            # the loop thread always sets done (or the host is stopped)
            import time as _time

            deadline = None if timeout is None else _time.monotonic() + timeout
            while not op.done.wait(timeout=0.05):
                if self._stop.is_set():
                    raise ErrStopped()
                if cancel is not None and cancel.is_set():
                    if not op.try_cancel():
                        # the loop already won the transition and is executing
                        # it (the reference's ctx race: the proposal proceeds);
                        # keep waiting for it to finish
                        continue
                    # we won: the loop is guaranteed to skip it
                    raise ErrCanceled()
                if deadline is not None and _time.monotonic() > deadline:
                    cancel.set()  # belt-and-braces for external observers
                    if not op.try_cancel():
                        continue  # already executing: let it finish
                    raise TimeoutError(f"{kind} timed out after {timeout}s")
            if op.error is not None:
                raise op.error
            return op.result
        return None


class Node:
    """Per-lane async handle (reference: node.go:132-243)."""

    def __init__(self, host: NodeHost, lane: int):
        self.host = host
        self.lane = lane

    def tick(self):
        self.host._submit("tick", self.lane)

    def campaign(self):
        self.host._submit("campaign", self.lane)

    def propose(
        self,
        data: bytes,
        wait: bool = True,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ):
        """Blocking like the reference (node.go:469 Propose -> stepWait):
        returns once the proposal was stepped (raising ErrProposalDropped if
        refused), or raises TimeoutError / ErrCanceled / ErrStopped on the
        ctx-equivalent arms (node.go:502-545)."""
        self.host._submit(
            "propose", self.lane, data, wait=wait, timeout=timeout, cancel=cancel
        )

    def propose_conf_change(
        self,
        data: bytes,
        v2: bool = False,
        wait: bool = True,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ):
        self.host._submit(
            "propose_cc", self.lane, (data, v2),
            wait=wait, timeout=timeout, cancel=cancel,
        )

    def step(
        self,
        msg: Message,
        wait: bool = False,
        timeout: float | None = None,
        cancel: threading.Event | None = None,
    ):
        """Non-blocking for network messages (reference node.Step); pass
        wait=True for the stepWait contract on local proposals."""
        if msg.type in LOCAL_MSGS and msg.frm not in (
            LOCAL_APPEND_THREAD,
            LOCAL_APPLY_THREAD,
        ):
            # reference: node.go:525-530 — local messages are silently
            # ignored by node.Step; here we reject loudly so misuse of the
            # tick/campaign/report_* APIs is visible. Storage-thread acks
            # (async-storage mode) pass, as in rawnode.go:108-125.
            raise ValueError("cannot step raft local message")
        self.host._submit(
            "step", self.lane, msg, wait=wait, timeout=timeout, cancel=cancel
        )

    def ready(self, timeout: float | None = None) -> Ready:
        """Blocking receive, like `<-n.Ready()` (reference: node.go:547)."""
        return self.host._ready_q[self.lane].get(timeout=timeout)

    def has_ready(self) -> bool:
        return not self.host._ready_q[self.lane].empty()

    def advance(self):
        self.host._submit("advance", self.lane)

    def apply_conf_change(self, cc):
        return self.host._submit("apply_cc", self.lane, cc, wait=True)

    def transfer_leadership(self, transferee: int):
        self.host._submit("transfer", self.lane, transferee)

    def read_index(self, ctx: int):
        self.host._submit("read_index", self.lane, ctx)

    def report_unreachable(self, peer: int):
        self.host._submit("report_unreachable", self.lane, peer)

    def report_snapshot(self, peer: int, ok: bool):
        self.host._submit("report_snapshot", self.lane, (peer, ok))

    def status(self) -> dict:
        return self.host._submit("status", self.lane, wait=True)

    def compact(self, to_index: int, data: bytes = b""):
        self.host._submit("compact", self.lane, (to_index, data), wait=True)

    def stop(self):
        self.host.stop()
