"""Host-side RawNode facade over the batched device engine.

The reference's `RawNode` (reference: rawnode.go:34-559) is a thread-unsafe,
allocation-light driver around one `raft` state machine: the application calls
`Step/Propose/Tick`, collects a `Ready` bundle (reference: node.go:52-115),
persists + sends, then calls `Advance`. Here the same contract is exposed
per *lane* of the batched engine: a `RawNodeBatch` hosts N raft nodes in one
device-resident `RaftState`, and `RawNode(batch, lane)` is the familiar
single-node view.

The device holds all algorithmic state (terms, votes, progress, log window of
(term, type, size) columns); entry *payloads* live host-side in an
`EntryStore` keyed by (lane, index), mirroring SURVEY §7's state layout. The
Ready/Advance cycle is faithful to the sync-mode contract (reference:
doc.go:69-145):

  - `Ready.entries` = the unstable tail (stabled, last] to persist;
  - `Ready.committed_entries` = (applied, committed] to apply;
  - `Ready.messages` = peer-addressed emissions, valid to send only after
    the entries/HardState in the same Ready are durable;
  - after-append self-messages (reference: msgsAfterAppend, raft.go:534-580)
    are held back and stepped during `advance()`, exactly like
    `RawNode.acceptReady`/`Advance` (reference: rawnode.go:404-440, 479-491).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache, partial
from typing import Iterable

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu.config import Shape
from raft_tpu.messages import MsgBatch, empty_batch
from raft_tpu.ops import step as stepmod
from raft_tpu.state import LaneConfig, RaftState, init_state, make_lane_config
from raft_tpu.types import (
    LOCAL_APPEND_THREAD,
    LOCAL_APPLY_THREAD,
    LOCAL_MSGS,
    EntryType,
    MessageType as MT,
    ProgressState,
    StateType,
)

I32 = jnp.int32


# Typed refusal taxonomy: every reference drop path test_backpressure.py
# audits, named so callers (and the serving frontend's admission layer,
# raft_tpu/serve/admission.py, which mirrors these as Rejected(reason))
# can react per-cause instead of string-matching a message.
DROP_NO_LEADER = "no_leader"  # raft.go:1671-1675
DROP_CANDIDATE = "candidate"  # raft.go:1636-1642
DROP_TRANSFERRING = "transferring"  # raft.go:1256-1258
DROP_FORWARDING_DISABLED = "forwarding_disabled"  # raft.go:1676-1679
DROP_WINDOW_FULL = "window_full"  # device log window (engine static bound)
DROP_UNCOMMITTED_FULL = "uncommitted_full"  # raft.go:2033-2047
DROP_UNKNOWN = "dropped"


class ErrProposalDropped(Exception):
    """The proposal was not appended or forwarded — retry later (reference:
    raft.go:30 ErrProposalDropped; returned by Step/Propose so the caller
    can react, node.go:469). `reason` carries the DROP_* cause."""

    def __init__(self, reason: str = DROP_UNKNOWN):
        super().__init__(reason)
        self.reason = reason


# --------------------------------------------------------------------------
# host-level data model (the raftpb analog)


@dataclasses.dataclass
class Entry:
    """reference: raftpb/raft.proto:21-26."""

    term: int = 0
    index: int = 0
    type: int = int(EntryType.ENTRY_NORMAL)
    data: bytes = b""


@dataclasses.dataclass
class Snapshot:
    """reference: raftpb/raft.proto:27-39 (data + metadata)."""

    index: int = 0
    term: int = 0
    data: bytes = b""
    voters: tuple = ()
    learners: tuple = ()
    voters_outgoing: tuple = ()
    learners_next: tuple = ()
    auto_leave: bool = False


@dataclasses.dataclass
class Message:
    """Host-level raftpb.Message (reference: raftpb/raft.proto:71-108)."""

    type: int
    to: int = 0
    frm: int = 0
    term: int = 0
    log_term: int = 0
    index: int = 0
    commit: int = 0
    vote: int = 0
    reject: bool = False
    reject_hint: int = 0
    # int = engine ticket; bytes = foreign wire context (e.g. a Go peer's
    # ReadIndex id), interned to a negative ticket at the engine boundary
    context: int | bytes = 0
    entries: list = dataclasses.field(default_factory=list)
    snapshot: Snapshot | None = None
    # async-storage-writes: messages to deliver once this message's work is
    # done (reference: raftpb/raft.proto:104-107 Responses)
    responses: list = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class HardState:
    """reference: raftpb/raft.proto:110-114."""

    term: int = 0
    vote: int = 0
    commit: int = 0

    def is_empty(self) -> bool:
        return self == HardState()


@dataclasses.dataclass
class SoftState:
    """reference: node.go:35-43."""

    lead: int = 0
    raft_state: int = int(StateType.FOLLOWER)


@dataclasses.dataclass
class Ready:
    """reference: node.go:52-115."""

    soft_state: SoftState | None = None
    hard_state: HardState | None = None
    entries: list = dataclasses.field(default_factory=list)
    committed_entries: list = dataclasses.field(default_factory=list)
    messages: list = dataclasses.field(default_factory=list)
    snapshot: Snapshot | None = None
    read_states: list = dataclasses.field(default_factory=list)
    must_sync: bool = False

    def contains_updates(self) -> bool:
        return bool(
            self.soft_state
            or (self.hard_state and not self.hard_state.is_empty())
            or self.entries
            or self.committed_entries
            or self.messages
            or self.snapshot
            or self.read_states
        )


@dataclasses.dataclass
class ReadState:
    """reference: read_only.go:24-27."""

    index: int
    request_ctx: int | bytes


def _sov(x: int) -> int:
    """Protobuf varint encoding size (reference: raftpb/raft.pb.go sovRaft)."""
    n = 1
    while x >= 0x80:
        x >>= 7
        n += 1
    return n


def entry_go_size(e: Entry) -> int:
    """Byte-exact raftpb.Entry.Size() (generated gogoproto marshal size) so
    size-based pagination decisions match the reference bit-for-bit. Empty
    payloads are nil Data in Go and marshal no Data field (raft.pb.go guards
    `if m.Data != nil`)."""
    n = 1 + _sov(e.term) + 1 + _sov(e.index) + 1 + _sov(e.type)
    if e.data:
        n += 1 + _sov(len(e.data)) + len(e.data)
    return n


class EntryStore:
    """Host-side payload store: (lane, index) -> (term, type, data).

    The columnar half of the reference's MemoryStorage (storage.go:98-310) —
    the device keeps (term, type, size) columns; this keeps the bytes.
    """

    def __init__(self, n_lanes: int):
        self._d: list[dict[int, tuple[int, int, bytes]]] = [
            {} for _ in range(n_lanes)
        ]
        self._snap: list[Snapshot | None] = [None] * n_lanes

    def put(self, lane: int, e: Entry):
        # nil payloads (wire Data absent) normalize to b"" at the store
        # boundary — the engine never distinguishes them (Go doesn't either)
        self._d[lane][e.index] = (e.term, e.type, e.data or b"")

    def get(self, lane: int, index: int, term: int) -> tuple[int, bytes]:
        rec = self._d[lane].get(index)
        if rec is None or (term and rec[0] != term):
            return (0, b"")
        return (rec[1], rec[2])

    def truncate_from(self, lane: int, index: int):
        d = self._d[lane]
        for i in [i for i in d if i >= index]:
            del d[i]

    def compact_below(self, lane: int, index: int):
        d = self._d[lane]
        for i in [i for i in d if i < index]:
            del d[i]

    def set_snapshot(self, lane: int, snap: Snapshot | None):
        self._snap[lane] = snap

    def snapshot(self, lane: int) -> Snapshot | None:
        return self._snap[lane]


# --------------------------------------------------------------------------
# MsgBatch <-> Message conversion


_MSG_SCALARS = (
    ("type", "type"),
    ("to", "to"),
    ("frm", "frm"),
    ("term", "term"),
    ("log_term", "log_term"),
    ("index", "index"),
    ("commit", "commit"),
    ("reject", "reject"),
    ("reject_hint", "reject_hint"),
    ("context", "context"),
)


@lru_cache(maxsize=1)
def _scan_fn():
    """Module-level jit of the step_many multi-column scan: shared across
    RawNodeBatch instances (per-instance jit wrappers recompile the same
    program — see _compiled_kernels)."""
    from raft_tpu.cluster import scan_step

    return jax.jit(scan_step)


@lru_cache(maxsize=8)
def _zero_inbox_template(n: int, m: int, e: int):
    """Host-side zeroed MsgBatch columns (dtypes from the device spec,
    fetched once): the scratch buffers _flush_batch copies from."""
    base = empty_batch((n, m), e)
    return {
        f.name: np.asarray(getattr(base, f.name))
        for f in dataclasses.fields(base)
    }


def _zero_inbox_cols(n: int, m: int, e: int) -> dict:
    return {k: v.copy() for k, v in _zero_inbox_template(n, m, e).items()}


def _msg_to_row(msg: Message, e: int) -> dict:
    row = {b: getattr(msg, h) for h, b in _MSG_SCALARS}
    if msg.type == int(MT.MSG_PROP) and any(
        x.type == int(EntryType.ENTRY_CONF_CHANGE_V2) for x in msg.entries
    ):
        # per-entry leave-joint bitmask for the device's conf-change gating
        # (bit k set = entry k is a semantically-empty V2)
        from raft_tpu import confchange as _ccm

        bits = 0
        for k, x in enumerate(msg.entries[:e]):
            if x.type == int(
                EntryType.ENTRY_CONF_CHANGE_V2
            ) and _ccm.decode(x.data or b"", v1=False).leave_joint():
                bits |= 1 << k
        row["context"] = bits
    ents = msg.entries[:e]
    row["n_ents"] = len(ents)
    row["ent_term"] = [x.term for x in ents] + [0] * (e - len(ents))
    row["ent_type"] = [x.type for x in ents] + [0] * (e - len(ents))
    row["ent_bytes"] = [len(x.data or b"") for x in ents] + [0] * (e - len(ents))
    snap = msg.snapshot
    row["snap_index"] = snap.index if snap else 0
    row["snap_term"] = snap.term if snap else 0
    row["vote"] = 0
    return row


class _StateView:
    """Cached numpy view of the device state, refreshed after kernel calls.

    `version` stamps every refresh: between two refreshes each field is
    pulled D2H at most once (the first access), so repeated has_ready /
    ready calls between steps never re-transfer — consumers key derived
    caches (the batched egress bundle) on it. `transfers` counts the
    per-field D2H pulls; tests/test_egress.py asserts it stays flat across
    repeated polls of an unchanged state."""

    def __init__(self):
        self._cache = None
        self._state = None
        self.version = 0
        self.transfers = 0

    def refresh(self, state: RaftState):
        self._state = state
        self._cache = {}
        self.version += 1

    def __getattr__(self, name):
        if self._cache is None:
            raise AttributeError(name)
        if name not in self._cache:
            self.transfers += 1
            self._cache[name] = np.asarray(getattr(self._state, name))
        return self._cache[name]


# --------------------------------------------------------------------------


@lru_cache(maxsize=None)
def _compiled_kernels(max_entries: int):
    """Process-wide jit wrappers shared by every batch: jax caches compiled
    programs per wrapper instance, so per-batch wrappers would recompile the
    step kernel for every RawNodeBatch constructed (brutal in test suites)."""
    return (
        jax.jit(partial(stepmod.step, max_entries=max_entries)),
        jax.jit(lambda s, m: stepmod.tick(s, max_entries, m)),
        jax.jit(partial(stepmod.post_conf_change, max_entries=max_entries)),
        jax.jit(
            lambda s, m, p: stepmod.drain_appends(
                s, m, p, max_entries=max_entries
            )
        ),
    )


# outbound message type -> metrics counter family (MSG_SNAP folds into
# msgs_app like the device plane: a snapshot IS the append path's catch-up)
_MSG_COUNTER = {
    int(MT.MSG_APP): "msgs_app",
    int(MT.MSG_SNAP): "msgs_app",
    int(MT.MSG_APP_RESP): "msgs_app_resp",
    int(MT.MSG_HEARTBEAT): "msgs_heartbeat",
    int(MT.MSG_HEARTBEAT_RESP): "msgs_heartbeat_resp",
    int(MT.MSG_VOTE): "msgs_vote",
    int(MT.MSG_VOTE_RESP): "msgs_vote_resp",
}


class RawNodeBatch:
    """N RawNodes resident in one device batch."""

    def __init__(
        self,
        shape: Shape,
        ids: Iterable[int],
        peers: np.ndarray,
        learners: np.ndarray | None = None,
        seed: int = 1,
        cfg: LaneConfig | None = None,
        **cfg_overrides,
    ):
        self.shape = shape
        n = shape.n
        if cfg is None:
            cfg = make_lane_config(shape, **cfg_overrides)
        self.state = init_state(
            shape, np.asarray(list(ids), np.int32), peers, learners, seed=seed, cfg=cfg
        )
        # C++ payload arena when buildable; Python EntryStore otherwise
        from raft_tpu.runtime.native import make_payload_store

        self.store = make_payload_store(n)
        # optional single-lane step observer (the conformance harness's log
        # oracle): trace.snapshot(lane) before, trace.after_step(...) after
        self.trace = None
        self.view = _StateView()
        self.view.refresh(self.state)
        # host-plane observability counters, same snapshot schema as the
        # device plane (raft_tpu/metrics/); counted at the Ready surface
        from raft_tpu.metrics.host import HostCounters

        self.metrics = HostCounters()
        self._msgs: list[list[Message]] = [[] for _ in range(n)]
        self._after_append: list[list[Message]] = [[] for _ in range(n)]
        self._steps_on_advance: list[list[Message]] = [[] for _ in range(n)]
        # async-storage-writes bookkeeping (reference: doc.go:172-258):
        # _async gates the Ready shape; _inprog mirrors unstable
        # offsetInProgress; _applying mirrors the accepted applying cursor
        self._async = [False] * n
        self._inprog = [0] * n
        # staged-snapshot index already handed to the append thread; while it
        # matches pending_snap_index the snapshot is withheld from Ready —
        # unstable.snapshotInProgress (reference: log_unstable.go:49-56,
        # nextSnapshot:84-90)
        self._snap_inprog = [0] * n
        self._applying = [0] * n
        self._prev_hs = [HardState() for _ in range(n)]
        self._prev_ss = [SoftState() for _ in range(n)]
        self._read_states: list[list[ReadState]] = [[] for _ in range(n)]
        # foreign (bytes) contexts <-> negative device tickets; the device
        # only ever needs equality on the i32 ticket (ro_ctx ring / heartbeat
        # echo), the original bytes are restored on every host-visible surface
        # per-lane: ctx is a per-group request key (the reference's readOnly
        # queue is per raft instance, read_only.go:39-43) — identical bytes
        # on two lanes are distinct requests and must not share a ticket's
        # lifetime
        self._ctx_intern: list[dict[bytes, int]] = [{} for _ in range(shape.n)]
        self._ctx_rev: list[dict[int, bytes]] = [{} for _ in range(shape.n)]
        # monotonic: a released ticket is never reissued, so a live pending
        # request can't have its _ctx_rev entry clobbered by a later intern
        self._next_ctx_ticket = -2
        # egress plane (raft_tpu/ops/ready_mask.py): RAFT_TPU_EGRESS is
        # read at construction like the metrics plane; when off,
        # ready_lanes() falls back to the scalar per-lane poll and the
        # mask kernel is never traced. The cached bundle is keyed on
        # (view.version, host epoch): the epoch covers readiness-relevant
        # host mutations that don't touch device state (acceptReady's
        # cursor updates, async-mode toggles).
        from raft_tpu.ops import ready_mask as _rmask

        self._egress_on = _rmask.egress_enabled()
        self._bundle = None
        self._bundle_key = None
        self._host_epoch = 0
        e = shape.max_msg_entries
        (
            self._step_fn,
            self._tick_fn,
            self._post_cc_fn,
            self._drain_fn,
        ) = _compiled_kernels(e)

    # -- kernel plumbing ---------------------------------------------------

    def _ctx_ticket(self, lane: int, ctx) -> int:
        """Map a message context to the device's i32 ticket: ints pass
        through; foreign byte strings intern to a negative ticket (app int
        tickets are conventionally >= 0; engine-internal contexts are small
        positives). Repeated arrivals of the same bytes on the same lane
        (heartbeat echoes of a pending request's ctx) reuse the ticket —
        device-side ack matching is ticket equality."""
        if not isinstance(ctx, bytes):
            return int(ctx)
        if not ctx:
            return 0
        t = self._ctx_intern[lane].get(ctx)
        if t is None:
            t = self._next_ctx_ticket
            self._next_ctx_ticket -= 1
            self._ctx_intern[lane][ctx] = t
            self._ctx_rev[lane][t] = ctx
        return t

    def _ctx_out(self, lane: int, ticket: int):
        """Restore the original bytes for interned tickets."""
        return self._ctx_rev[lane].get(ticket, ticket)

    def _ctx_release(self, lane: int, ticket: int):
        """Drop an interned mapping once its last engine artifact (the
        ReadState or the MsgReadIndexResp back to the requester) has been
        surfaced — the intern table must not grow with request count."""
        b = self._ctx_rev[lane].pop(ticket, None)
        if b is not None:
            self._ctx_intern[lane].pop(b, None)

    def _inbox_one(self, lane: int, msg: Message) -> MsgBatch:
        # assembled host-side in numpy and shipped as ONE transfer per
        # column — per-field eager `.at[].set` device ops made this the
        # serving path's hottest line (a dispatch per field per message)
        n, e = self.shape.n, self.shape.max_msg_entries
        base = empty_batch((n,), e)
        row = _msg_to_row(msg, e)
        upd = {}
        for f in dataclasses.fields(base):
            arr = getattr(base, f.name)
            col = np.zeros(arr.shape, arr.dtype)
            if f.name == "type":
                col[:] = int(MT.MSG_NONE)
            col[lane] = np.asarray(row[f.name])
            upd[f.name] = jnp.asarray(col)
        return MsgBatch(**upd)

    def _collect_out(
        self, out: MsgBatch, exclude_lane_msgs: bool = False, src_msg=None
    ):
        """Move kernel emissions into per-lane host queues."""
        v = self.shape.v
        types = np.asarray(out.type)
        hot = np.nonzero(types != int(MT.MSG_NONE))
        if len(hot[0]) == 0:
            return
        cols = {name: np.asarray(getattr(out, name)) for name in (
            "type", "to", "frm", "term", "log_term", "index", "commit",
            "reject", "reject_hint", "context", "n_ents", "ent_term",
            "ent_type", "ent_bytes", "snap_index", "snap_term",
        )}
        for lane, slot in zip(*hot):
            lane, slot = int(lane), int(slot)
            ctx_ticket = int(cols["context"][lane, slot])
            m = Message(
                type=int(cols["type"][lane, slot]),
                to=int(cols["to"][lane, slot]),
                frm=int(cols["frm"][lane, slot]),
                term=int(cols["term"][lane, slot]),
                log_term=int(cols["log_term"][lane, slot]),
                index=int(cols["index"][lane, slot]),
                commit=int(cols["commit"][lane, slot]),
                reject=bool(cols["reject"][lane, slot]),
                reject_hint=int(cols["reject_hint"][lane, slot]),
                context=self._ctx_out(lane, ctx_ticket),
            )
            if m.type == int(MT.MSG_READ_INDEX_RESP):
                # the response is this ticket's final engine artifact
                self._ctx_release(lane, ctx_ticket)
                if m.to == int(self.view.id[lane]):
                    # a locally-requested read resolves synchronously into
                    # readStates, never onto the wire (reference:
                    # raft.go:1318-1331, 2081-2097 responseToReadIndexReq
                    # with req.From in {None, r.id}) — the very next Ready
                    # carries it
                    self._read_states[lane].append(
                        ReadState(index=m.index, request_ctx=m.context)
                    )
                    continue
            ne = int(cols["n_ents"][lane, slot])
            if ne and m.type == int(MT.MSG_PROP):
                # proposal forwarded to the leader: entries ride verbatim with
                # unset term/index (reference: raft.go:1682-1684)
                if src_msg is not None:
                    m.entries = [
                        Entry(term=0, index=0, type=x.type, data=x.data)
                        for x in src_msg.entries[:ne]
                    ]
            elif ne:
                base_index = m.index
                for k in range(ne):
                    term = int(cols["ent_term"][lane, slot, k])
                    idx = base_index + 1 + k
                    etype, data = self.store.get(lane, idx, term)
                    m.entries.append(
                        Entry(
                            term=term,
                            index=idx,
                            type=int(cols["ent_type"][lane, slot, k]),
                            data=data,
                        )
                    )
            si = int(cols["snap_index"][lane, slot])
            if m.type == int(MT.MSG_SNAP):
                # resolve the app snapshot the kernel referenced by index
                # (Storage.Snapshot() semantics — carries its own ConfState)
                snap = self.store.snapshot(lane)
                if snap is not None and snap.index == si:
                    m.snapshot = snap
                else:
                    m.snapshot = Snapshot(
                        index=si,
                        term=int(cols["snap_term"][lane, slot]),
                        voters=self.peer_ids(lane, voters=True),
                        learners=self.peer_ids(lane, learners=True),
                    )
            # reference send() rule (raft.go:534-580): MsgAppResp/MsgVoteResp/
            # MsgPreVoteResp — to ANY target — are predicated on unstable
            # state and wait for the append to be durable (msgsAfterAppend);
            # everything else is immediately sendable. Self-addressed
            # non-response messages (own ReadIndex release) also wait.
            if m.type in (
                int(MT.MSG_APP_RESP),
                int(MT.MSG_VOTE_RESP),
                int(MT.MSG_PRE_VOTE_RESP),
            ) or m.to == int(self.view.id[lane]):
                self._after_append[lane].append(m)
            else:
                self._msgs[lane].append(m)

    def _run_step(self, lane: int, msg: Message):
        """One kernel invocation with a single hot lane; payload bookkeeping."""
        if isinstance(msg.context, bytes):
            msg = dataclasses.replace(
                msg, context=self._ctx_ticket(lane, msg.context)
            )
        pre = self.trace.snapshot(lane) if self.trace is not None else None
        old_last = int(self.view.last[lane])
        old_term = int(self.view.term[lane])
        old_lt = old_stabled = None
        old_psi = int(self.view.pending_snap_index[lane])
        if self._async[lane]:
            old_lt = np.array(self.view.log_term[lane])
            old_stabled = int(self.view.stabled[lane])
        inbox = self._inbox_one(lane, msg)
        self.state, out = self._step_fn(self.state, inbox)
        self.view.refresh(self.state)
        if old_lt is not None:
            self._rewind_inprog(lane, old_lt, old_stabled, old_last)
            # a restore replaces the staged snapshot (snapshotInProgress :=
            # false, log_unstable.go:188-194) and an append-thread ack clears
            # it — either way the marker no longer matches what is staged
            new_psi = int(self.view.pending_snap_index[lane])
            if new_psi != old_psi or (msg.type == int(MT.MSG_SNAP) and new_psi):
                self._snap_inprog[lane] = 0
        # payloads first: fan-out messages emitted by this same step resolve
        # their entry bytes from the store
        self._store_accepted_payloads(lane, msg, old_last, old_term)
        if self.trace is not None:
            self.trace.after_step(lane, msg, pre)
        self._collect_out(out, src_msg=msg)
        # post-ack drain loop (reference: raft.go:1516-1518): an accepted
        # MsgAppResp may have freed several inflight slots / switched the
        # peer to replicate — keep sending until flow control pauses
        if (
            msg.type == int(MT.MSG_APP_RESP)
            and not msg.reject
            and msg.frm != self.id_of(lane)  # raft.go:1515 `r.id != m.From`
        ):
            self._drain(lane, msg.frm)

    def _drain(self, lane: int, peer_id: int):
        cap = int(np.asarray(self.state.cfg.max_inflight[lane])) + 1
        mask = peer = None
        for _ in range(cap):
            if not self._has_send_backlog(lane, peer_id):
                break
            if mask is None:
                mask = jnp.zeros((self.shape.n,), bool).at[lane].set(True)
                peer = jnp.zeros((self.shape.n,), I32).at[lane].set(peer_id)
            self.state, out = self._drain_fn(self.state, mask, peer)
            self.view.refresh(self.state)
            if not (np.asarray(out.type) != int(MT.MSG_NONE)).any():
                break
            self._collect_out(out)

    def _has_send_backlog(self, lane: int, peer_id: int) -> bool:
        """Host-side fast path for the drain loop: does the acking peer
        still have unsent entries and room? (Mirrors maybe_send_append's
        gate coarsely — the kernel re-checks exactly.)"""
        v = self.view
        if int(v.state[lane]) != int(StateType.LEADER):
            return False
        ids = v.prs_id[lane]
        sel = ids == peer_id
        if not sel.any() or peer_id == int(v.id[lane]):
            return False
        backlog = v.pr_next[lane] <= int(v.last[lane])
        ps = v.pr_state[lane]
        full = v.infl_count[lane] >= int(
            np.asarray(self.state.cfg.max_inflight[lane])
        )
        paused = (
            ((ps == int(ProgressState.PROBE)) & v.pr_msg_app_flow_paused[lane])
            | ((ps == int(ProgressState.REPLICATE)) & full)
            | (ps == int(ProgressState.SNAPSHOT))
        )
        return bool((sel & backlog & ~paused).any())

    def _rewind_inprog(self, lane: int, old_lt, old_stabled: int, old_last: int):
        """Mirror of unstable.truncateAndAppend's offsetInProgress rewind
        (reference: log_unstable.go:196-234): entries handed to the storage
        thread that were truncated/overwritten must be re-emitted in the next
        Ready."""
        w = self.shape.w
        new_last = int(self.view.last[lane])
        inprog = min(self._inprog[lane], new_last)
        hi = min(inprog, old_last)
        lt = self.view.log_term[lane]
        # a conflicting append can also rewind the stable cursor itself, so
        # scan from the smaller of the old/new stable points
        lo = min(old_stabled, int(self.view.stabled[lane]))
        for i in range(lo + 1, hi + 1):
            if int(lt[i & (w - 1)]) != int(old_lt[i & (w - 1)]):
                inprog = i - 1
                break
        self._inprog[lane] = inprog

    def _store_accepted_payloads(
        self, lane: int, msg: Message, old_last: int, old_term: int
    ):
        if not msg.entries:
            return
        w = self.shape.w
        log_term = self.view.log_term[lane]
        log_type = self.view.log_type[lane]
        last = int(self.view.last[lane])
        cur_term = int(self.view.term[lane])
        if msg.type == int(MT.MSG_PROP):
            # device stamped entries with the lane's current term at old_last+
            for k, e in enumerate(msg.entries):
                idx = old_last + 1 + k
                if idx <= last and int(log_term[idx & (w - 1)]) == cur_term:
                    etype = int(log_type[idx & (w - 1)])
                    # a conf change refused by gating was appended as an
                    # EMPTY normal entry (reference: raft.go:1291-1295)
                    data = e.data if etype == e.type else b""
                    self.store.put(lane, Entry(cur_term, idx, etype, data))
        else:  # MsgApp
            for e in msg.entries:
                if e.index <= last and int(log_term[e.index & (w - 1)]) == e.term:
                    self.store.put(lane, Entry(e.term, e.index, e.type, e.data))

    # -- public API (the RawNode method set, reference rawnode.go) ---------

    def step(self, lane: int, msg: Message):
        """reference: rawnode.go:108-125 — every local message type is
        rejected (ErrStepLocalMsg) unless it comes from a local storage
        thread (MsgStorageAppendResp/MsgStorageApplyResp with From in
        {LocalAppendThread, LocalApplyThread}); use tick()/campaign()/
        report_unreachable()/report_snapshot() for the local inputs."""
        local_target = msg.frm in (LOCAL_APPEND_THREAD, LOCAL_APPLY_THREAD)
        if msg.type in LOCAL_MSGS and not local_target:
            raise ValueError(f"cannot step raft local message {msg.type}")
        if msg.type == int(MT.MSG_STORAGE_APPLY_RESP) and msg.entries:
            # the kernel's apply-ack convention: last applied index rides
            # msg.index, applied payload bytes ride msg.commit
            msg = dataclasses.replace(
                msg,
                index=msg.entries[-1].index,
                commit=sum(len(e.data or b"") for e in msg.entries),
                entries=[],
            )
        if msg.type == int(MT.MSG_PROP):
            # Step(MsgProp) surfaces ErrProposalDropped like the reference
            # (rawnode.go:108-125 -> raft.Step); transports deciding to
            # drop-and-forget catch it
            self._step_prop(lane, msg)
            return
        self._run_step(lane, msg)
        # async mode: appliedTo may arm the auto-leave proposal
        # (reference: raft.go:717-745); sync mode does this in advance()
        if msg.type == int(MT.MSG_STORAGE_APPLY_RESP) and self._async[lane]:
            self._maybe_auto_leave(lane)
        if msg.type == int(MT.MSG_SNAP) and msg.snapshot is not None:
            snap = msg.snapshot
            if int(self.view.pending_snap_index[lane]) == snap.index:
                # restore accepted on device: adopt the snapshot's ConfState
                # (reference: raft.go:1835-1850 restore -> switchToConfig)
                # and the payload state host-side
                from raft_tpu import confchange as ccm

                cs = ccm.ConfState(
                    voters=tuple(snap.voters),
                    learners=tuple(snap.learners),
                    voters_outgoing=tuple(snap.voters_outgoing),
                    learners_next=tuple(snap.learners_next),
                    auto_leave=snap.auto_leave,
                )
                cfg, trk = ccm.restore(cs, last_index=snap.index)
                self._write_tracker(lane, cfg, trk)
                self.set_app_snapshot(lane, snap)
                self.store.compact_below(lane, snap.index + 1)

    # -- batched stepping (the serving-path fast lane) ---------------------

    _BATCH_M = 4  # inbox columns per device dispatch

    def _batchable(self, lane: int, msg: Message) -> bool:
        """Messages steppable in a shared multi-column dispatch: the fan-in
        hot path (acks, votes, heartbeats, ReadIndex traffic — the
        reference's raft.go:1333-1526 hot loop). Excluded and stepped
        per-message: anything carrying entries/snapshots (payload-store and
        ErrProposalDropped bookkeeping are per-message), async-storage
        lanes (in-progress cursor rewind), and traced lanes (the
        conformance log oracle observes single steps)."""
        return (
            self.trace is None
            and not self._async[lane]
            and not msg.entries
            and msg.snapshot is None
            and msg.type
            not in (
                int(MT.MSG_PROP),
                int(MT.MSG_SNAP),
            )
            # every local type takes the per-message path so step() applies
            # the full rawnode.go:108-125 filter (ValueError for local
            # messages unless from a storage thread) instead of the batched
            # fast lane silently applying e.g. a forged MsgStorageApplyResp
            and msg.type not in LOCAL_MSGS
        )

    def step_many(self, steps, on_drop=None):
        """Step (lane, message) pairs in submission order with at most one
        device dispatch per _BATCH_M batchable messages, instead of one per
        message (the host-device round-trip amortization VERDICT r2 #4 asks
        of the serving path). Non-batchable messages flush the current
        batch (order preserved) and take the per-message path;
        ErrProposalDropped from those goes to on_drop(lane, msg) when given,
        else propagates."""
        pending: list[tuple[int, Message]] = []
        per_lane: dict[int, int] = {}

        def flush():
            if pending:
                self._flush_batch(pending)
                pending.clear()
                per_lane.clear()

        for lane, msg in steps:
            if self._batchable(lane, msg):
                if per_lane.get(lane, 0) >= self._BATCH_M:
                    flush()
                if isinstance(msg.context, bytes):
                    msg = dataclasses.replace(
                        msg, context=self._ctx_ticket(lane, msg.context)
                    )
                pending.append((lane, msg))
                per_lane[lane] = per_lane.get(lane, 0) + 1
            else:
                flush()
                try:
                    self.step(lane, msg)
                except ErrProposalDropped:
                    if on_drop is None:
                        raise
                    on_drop(lane, msg)
        flush()

    def _flush_batch(self, pending):
        n, e, m_cols = self.shape.n, self.shape.max_msg_entries, self._BATCH_M
        cols = _zero_inbox_cols(n, m_cols, e)
        fill = [0] * n
        acks: list[tuple[int, int]] = []
        for lane, msg in pending:
            row = _msg_to_row(msg, e)
            s = fill[lane]
            fill[lane] += 1
            for name, val in row.items():
                cols[name][lane, s] = np.asarray(val)
            if (
                msg.type == int(MT.MSG_APP_RESP)
                and not msg.reject
                and msg.frm != self.id_of(lane)
                and (lane, msg.frm) not in acks
            ):
                acks.append((lane, msg.frm))
        inbox = MsgBatch(**{k: jnp.asarray(v) for k, v in cols.items()})
        self.state, out_all = _scan_fn()(self.state, inbox)
        self.view.refresh(self.state)
        self._collect_out(out_all)
        # post-ack drain loop per acking peer (reference: raft.go:1515-1518)
        for lane, frm in acks:
            self._drain(lane, frm)

    def campaign(self, lane: int):
        self.metrics.inc("elections_started")
        self._run_step(lane, Message(type=int(MT.MSG_HUP), to=self.id_of(lane)))

    def propose(self, lane: int, data: bytes):
        """Raises ErrProposalDropped when the proposal neither lands in the
        local log nor is forwarded to a leader (reference: node.go:469 /
        raft.go:1244-1302, 1671-1680)."""
        nid = self.id_of(lane)
        self._step_prop(
            lane,
            Message(
                type=int(MT.MSG_PROP), to=nid, frm=nid, entries=[Entry(data=data)]
            ),
        )

    def propose_conf_change(self, lane: int, cc_data: bytes, v2: bool = False):
        nid = self.id_of(lane)
        t = EntryType.ENTRY_CONF_CHANGE_V2 if v2 else EntryType.ENTRY_CONF_CHANGE
        self._step_prop(
            lane,
            Message(
                type=int(MT.MSG_PROP),
                to=nid,
                frm=nid,
                entries=[Entry(type=int(t), data=cc_data)],
            ),
        )

    def _step_prop(self, lane: int, msg: Message):
        """Step a MsgProp and surface ErrProposalDropped: accepted means the
        lane's log grew (leader append) or a forwarded MsgProp was emitted
        (follower with a known leader)."""
        old_last = int(self.view.last[lane])
        n_fwd_before = sum(
            1 for m in self._msgs[lane] if m.type == int(MT.MSG_PROP)
        )
        self._run_step(lane, msg)
        if int(self.view.last[lane]) > old_last:
            self.metrics.inc("proposals")
            return
        n_fwd = sum(1 for m in self._msgs[lane] if m.type == int(MT.MSG_PROP))
        if n_fwd > n_fwd_before:
            self.metrics.inc("proposals")
            return
        self.metrics.inc("proposals_dropped")
        raise ErrProposalDropped(self._drop_reason(lane, msg))

    def _drop_reason(self, lane: int, msg: Message) -> str:
        """Classify a refused proposal against the reference's drop paths
        (the test_backpressure.py audit set). Diagnosed from the post-step
        view — a dropped MsgProp leaves the lane's state untouched, so the
        gates that refused it still hold."""
        v = self.view
        st = int(v.state[lane])
        if st in (int(StateType.CANDIDATE), int(StateType.PRE_CANDIDATE)):
            return DROP_CANDIDATE
        if st == int(StateType.FOLLOWER):
            if int(v.lead[lane]) == 0:
                return DROP_NO_LEADER
            if bool(
                np.asarray(self.state.cfg.disable_proposal_forwarding)[lane]
            ):
                return DROP_FORWARDING_DISABLED
            return DROP_UNKNOWN
        if int(v.lead_transferee[lane]) != 0:
            return DROP_TRANSFERRING
        n_ents = max(1, len(msg.entries))
        if (
            int(v.last[lane]) + n_ents - int(v.snap_index[lane])
            > self.shape.w
        ):
            return DROP_WINDOW_FULL
        us = int(v.uncommitted_size[lane])
        sz = sum(len(e.data) for e in msg.entries)
        if us > 0 and sz > 0 and us + sz > int(
            np.asarray(self.state.cfg.max_uncommitted_size)[lane]
        ):
            return DROP_UNCOMMITTED_FULL
        return DROP_UNKNOWN

    def transfer_leadership(self, lane: int, transferee: int):
        self._run_step(
            lane,
            Message(
                type=int(MT.MSG_TRANSFER_LEADER),
                to=self.id_of(lane),
                frm=transferee,
            ),
        )

    def forget_leader(self, lane: int):
        self._run_step(lane, Message(type=int(MT.MSG_FORGET_LEADER), to=self.id_of(lane)))

    def report_unreachable(self, lane: int, peer: int):
        self._run_step(
            lane, Message(type=int(MT.MSG_UNREACHABLE), to=self.id_of(lane), frm=peer)
        )

    def report_snapshot(self, lane: int, peer: int, ok: bool):
        self._run_step(
            lane,
            Message(
                type=int(MT.MSG_SNAP_STATUS),
                to=self.id_of(lane),
                frm=peer,
                reject=not ok,
            ),
        )

    def read_index(self, lane: int, ctx: int | bytes):
        nid = self.id_of(lane)
        self._run_step(
            lane, Message(type=int(MT.MSG_READ_INDEX), to=nid, frm=nid, context=ctx)
        )

    def tick(self, lane: int):
        """reference: rawnode.go:69-73 + raft.go:823-862: tick fires local
        messages which are immediately stepped."""
        n = self.shape.n
        mask = jnp.zeros((n,), bool).at[lane].set(True)
        self.state, local = self._tick_fn(self.state, mask)
        self.view.refresh(self.state)
        lt = np.asarray(local.type)
        for s in range(lt.shape[1]):
            t = int(lt[lane, s])
            if t != int(MT.MSG_NONE):
                self._run_step(lane, Message(type=t, to=self.id_of(lane)))

    # -- Ready/Advance (reference: rawnode.go:141-200, 404-491) ------------

    def _host_dirty(self):
        """Invalidate the batched ready bundle after a host-only mutation
        of readiness-relevant cursors (device-state mutations invalidate
        via view.version instead)."""
        self._host_epoch += 1

    def _bundle_fresh(self) -> bool:
        return (
            self._bundle is not None
            and self._bundle_key == (self.view.version, self._host_epoch)
        )

    def _refresh_bundle(self):
        """Evaluate the batched ready predicate (ops/ready_mask.py) for all
        N lanes — ONE device dispatch + one transfer — unless the cached
        bundle still reflects (device state, host cursors)."""
        if self._bundle_fresh():
            return self._bundle
        from raft_tpu.ops import ready_mask as _rmask

        n = self.shape.n
        key = (self.view.version, self._host_epoch)
        host = _rmask.HostCursors(
            prev_term=np.array([h.term for h in self._prev_hs], np.int32),
            prev_vote=np.array([h.vote for h in self._prev_hs], np.int32),
            prev_commit=np.array([h.commit for h in self._prev_hs], np.int32),
            prev_lead=np.array([s.lead for s in self._prev_ss], np.int32),
            prev_state=np.array(
                [s.raft_state for s in self._prev_ss], np.int32
            ),
            host_pending=np.array(
                [
                    bool(
                        self._after_append[lane]
                        or self._msgs[lane]
                        or self._read_states[lane]
                    )
                    for lane in range(n)
                ],
                bool,
            ),
            is_async=np.array(self._async, bool),
            inprog=np.array(self._inprog, np.int32),
            snap_inprog=np.array(self._snap_inprog, np.int32),
            applying=np.array(self._applying, np.int32),
        )
        self._bundle = _rmask.compute_bundle(self.state, host)
        self._bundle_key = key
        self.metrics.inc(
            "egress_bytes", sum(a.nbytes for a in self._bundle)
        )
        return self._bundle

    def ready_lanes(self) -> list[int]:
        """Lanes with a pending Ready, evaluated batched in-device: one
        dispatch + one transfer instead of N scalar polls; the result is
        the kernel's cumsum-scatter-compacted active prefix (ascending
        lane order, like the scalar sweep). Falls back to the scalar
        has_ready sweep when RAFT_TPU_EGRESS=0.

        egress_lanes_scanned counts the lanes the HOST examined (N for the
        scalar sweep, only the active set on the batched path — the
        O(N) -> O(active) conversion the A/B bench asserts);
        egress_lanes_active counts the lanes surfaced."""
        n = self.shape.n
        if not self._egress_on:
            lanes = [lane for lane in range(n) if self.has_ready(lane)]
            self.metrics.inc("egress_lanes_scanned", n)
            self.metrics.inc("egress_lanes_active", len(lanes))
            return lanes
        bd = self._refresh_bundle()
        k = int(bd.count)
        self.metrics.inc("egress_lanes_scanned", k)
        self.metrics.inc("egress_lanes_active", k)
        return [int(x) for x in bd.active[:k]]

    def has_ready(self, lane: int) -> bool:
        """The reference's cheap predicate set (rawnode.go:450-472) — NOT a
        full Ready construction; this is the serving loop's poll and must
        stay O(1). Answers from the fresh batched bundle when one is cached
        (ready_lanes), falling back to the scalar path only when state
        mutated since the last refresh.
        tests/test_rawnode.py::test_has_ready_matches_peek keeps it
        equivalent to `ready(peek=True).contains_updates()`."""
        if (
            self._after_append[lane]
            or self._msgs[lane]
            or self._read_states[lane]
        ):
            return True
        if self._egress_on and self._bundle_fresh():
            return bool(self._bundle.ready[lane])
        return self._has_ready_scalar(lane)

    def _has_ready_scalar(self, lane: int) -> bool:
        """Per-lane scalar evaluation of the predicate — the batched
        kernel's twin (ops/ready_mask.py ready_bundle); the parity property
        test in tests/test_egress.py holds the two together."""
        if (
            self._after_append[lane]
            or self._msgs[lane]
            or self._read_states[lane]
        ):
            return True
        v = self.view
        if int(v.rs_count[lane]):
            return True
        ss = SoftState(int(v.lead[lane]), int(v.state[lane]))
        if ss != self._prev_ss[lane]:
            return True
        hs = HardState(
            int(v.term[lane]), int(v.vote[lane]), int(v.committed[lane])
        )
        if hs != self._prev_hs[lane] and not hs.is_empty():
            return True
        is_async = self._async[lane]
        last, stabled = int(v.last[lane]), int(v.stabled[lane])
        ent_lo = (
            max(stabled, min(self._inprog[lane], last)) if is_async else stabled
        )
        if last > ent_lo:
            return True
        raw_psi = int(v.pending_snap_index[lane])
        if raw_psi and not (is_async and self._snap_inprog[lane] == raw_psi):
            return True
        if is_async:
            lo = max(int(v.applied[lane]), self._applying[lane]) + 1
            hi = min(int(v.committed[lane]), stabled)
        else:
            lo, hi = int(v.applied[lane]) + 1, int(v.committed[lane])
        if raw_psi:
            hi = lo - 1  # the staged snapshot must apply first
        return hi >= lo

    def _lane_cursors(self, lane: int):
        """The scalar cursor set Ready construction needs: (term, vote,
        commit, lead, state, last, stabled, ent_lo, raw_psi, psi, lo, hi).
        Served from the fresh batched bundle when one is cached (no
        per-field scalar reads), else re-derived from the view with the
        exact same formulas."""
        if self._egress_on and self._bundle_fresh():
            bd = self._bundle
            return (
                int(bd.term[lane]), int(bd.vote[lane]), int(bd.commit[lane]),
                int(bd.lead[lane]), int(bd.state[lane]),
                int(bd.last[lane]), int(bd.stabled[lane]),
                int(bd.ent_lo[lane]), int(bd.psi_raw[lane]),
                int(bd.psi[lane]), int(bd.apply_lo[lane]),
                int(bd.apply_hi[lane]),
            )
        v = self.view
        is_async = self._async[lane]
        term, vote, commit = (
            int(v.term[lane]), int(v.vote[lane]), int(v.committed[lane])
        )
        lead, st = int(v.lead[lane]), int(v.state[lane])
        last, stabled = int(v.last[lane]), int(v.stabled[lane])
        ent_lo = (
            max(stabled, min(self._inprog[lane], last)) if is_async else stabled
        )
        raw_psi = int(v.pending_snap_index[lane])
        psi = (
            0 if (is_async and self._snap_inprog[lane] == raw_psi) else raw_psi
        )
        if is_async:
            lo = max(int(v.applied[lane]), self._applying[lane]) + 1
            hi = min(commit, stabled)
        else:
            lo, hi = int(v.applied[lane]) + 1, commit
        if raw_psi:
            hi = lo - 1  # snapshot must be applied first
        return (
            term, vote, commit, lead, st, last, stabled, ent_lo, raw_psi,
            psi, lo, hi,
        )

    def ready(self, lane: int, peek: bool = False) -> Ready:
        v = self.view
        nid = self.id_of(lane)
        is_async = self._async[lane]
        rd = Ready()
        # one cursor read: the fresh batched bundle when cached (no scalar
        # re-derivation), the view otherwise (_lane_cursors)
        (
            term, vote, commit, lead, st, last, stabled, ent_lo, raw_psi,
            psi, lo, hi,
        ) = self._lane_cursors(lane)
        hs = HardState(term, vote, commit)
        if hs != self._prev_hs[lane] and not hs.is_empty():
            rd.hard_state = hs
        ss = SoftState(lead, st)
        if ss != self._prev_ss[lane]:
            rd.soft_state = ss
        w = self.shape.w
        # unstable entries not yet handed to storage (async: skip in-progress;
        # reference log_unstable.go nextEntries/offsetInProgress)
        for i in range(ent_lo + 1, last + 1):
            t = int(v.log_term[lane, i & (w - 1)])
            etype, data = self.store.get(lane, i, t)
            rd.entries.append(Entry(t, i, int(v.log_type[lane, i & (w - 1)]), data))
        # pending snapshot to persist (reference Ready.Snapshot); in async
        # mode one already accepted by the append thread is withheld until
        # acked (unstable.nextSnapshot, log_unstable.go:84-90)
        if psi:
            snap = self.store.snapshot(lane)
            rd.snapshot = snap if snap and snap.index == psi else Snapshot(
                index=psi, term=int(v.pending_snap_term[lane])
            )
        # committed entries in [lo, hi], paginated by proto-encoding size
        # with limitSize's never-empty rule (log.go:216-240, util.go:266).
        # Sync mode applies from `applied`; async applies from the accepted
        # `applying` cursor and never applies unstable entries
        # (rawnode.go applyUnstableEntries); a staged snapshot empties the
        # window (it must be applied first, even one whose persistence is
        # still in flight on the append thread)
        budget = int(np.asarray(self.state.cfg.max_committed_size_per_ready[lane]))
        size = 0
        for i in range(lo, hi + 1):
            t = int(v.log_term[lane, i & (w - 1)])
            etype, data = self.store.get(lane, i, t)
            ent = Entry(t, i, int(v.log_type[lane, i & (w - 1)]), data)
            size += entry_go_size(ent)
            if rd.committed_entries and size > budget:
                break
            rd.committed_entries.append(ent)
        aa = self._after_append[lane]
        if is_async:
            rd.messages = list(self._msgs[lane])
        else:
            # sync mode: msgsAfterAppend to others ride this Ready's Messages
            # after r.msgs (reference: rawnode.go:177-186)
            rd.messages = list(self._msgs[lane]) + [m for m in aa if m.to != nid]
        # drain the device-side ReadState ring (reference: raft.go:371)
        nrs = int(v.rs_count[lane])
        rd.read_states = [
            ReadState(
                index=int(v.rs_index[lane, r]),
                request_ctx=self._ctx_out(lane, int(v.rs_ctx[lane, r])),
            )
            for r in range(nrs)
        ] + list(self._read_states[lane])
        # reference: rawnode.go:193-200 MustSync (entries, vote or term only)
        rd.must_sync = bool(
            rd.entries
            or term != self._prev_hs[lane].term
            or vote != self._prev_hs[lane].vote
        )
        if is_async:
            # storage-thread messages (reference: rawnode.go:202-399)
            if rd.entries or rd.hard_state or rd.snapshot or aa:
                rd.messages.append(self._storage_append_msg(lane, rd, aa))
            if rd.committed_entries:
                rd.messages.append(self._storage_apply_msg(lane, rd))
        if not peek:
            # count at the accept surface so a peeked Ready isn't double
            # counted; families mirror the device plane's counter names
            mx = self.metrics
            for m in rd.messages:
                fam = _MSG_COUNTER.get(m.type)
                if fam:
                    mx.inc(fam)
            if rd.committed_entries:
                mx.inc("commits", len(rd.committed_entries))
            if rd.read_states:
                mx.inc("read_index_served", len(rd.read_states))
            if rd.soft_state:
                prev = self._prev_ss[lane]
                if (
                    rd.soft_state.raft_state == int(StateType.LEADER)
                    and prev.raft_state != int(StateType.LEADER)
                ):
                    mx.inc("elections_won")
                if rd.soft_state.lead not in (0, prev.lead):
                    mx.inc("leader_changes")
            # acceptReady (reference rawnode.go:404-440)
            if rd.hard_state:
                self._prev_hs[lane] = rd.hard_state
            if rd.soft_state:
                self._prev_ss[lane] = rd.soft_state
            self._msgs[lane] = []
            self._read_states[lane] = []
            self._steps_on_advance[lane] = [m for m in aa if m.to == nid]
            self._after_append[lane] = []
            if is_async:
                if rd.entries:
                    self._inprog[lane] = rd.entries[-1].index
                if rd.snapshot:
                    # acceptInProgress: the append thread now owns it
                    # (reference: log_unstable.go:106-115)
                    self._snap_inprog[lane] = rd.snapshot.index
                if rd.committed_entries:
                    self._applying[lane] = rd.committed_entries[-1].index
            if nrs:
                for r_ in range(nrs):
                    self._ctx_release(lane, int(v.rs_ctx[lane, r_]))
                self.state = dataclasses.replace(
                    self.state, rs_count=self.state.rs_count.at[lane].set(0)
                )
                self.view.refresh(self.state)
            self._accepted = getattr(self, "_accepted", {})
            self._accepted[lane] = rd
            # acceptReady moved host-side cursors the device never saw
            # (prev hard/soft state, drained queues, in-progress marks)
            self._host_dirty()
        return rd

    def _storage_append_msg(self, lane: int, rd: Ready, aa: list) -> Message:
        """reference: rawnode.go:225-262 newStorageAppendMsg."""
        v = self.view
        nid = self.id_of(lane)
        m = Message(
            type=int(MT.MSG_STORAGE_APPEND),
            to=LOCAL_APPEND_THREAD,
            frm=nid,
            entries=list(rd.entries),
        )
        if rd.hard_state:
            m.term = rd.hard_state.term
            m.vote = rd.hard_state.vote
            m.commit = rd.hard_state.commit
        if rd.snapshot:
            m.snapshot = rd.snapshot
        m.responses = list(aa)
        last, stabled = int(v.last[lane]), int(v.stabled[lane])
        if last > stabled or rd.snapshot:
            # newStorageAppendRespMsg (rawnode.go:264-365): attests the full
            # unstable (index, term) with the ABA term guard
            resp = Message(
                type=int(MT.MSG_STORAGE_APPEND_RESP),
                to=nid,
                frm=LOCAL_APPEND_THREAD,
                term=int(v.term[lane]),
            )
            if last > stabled:
                resp.index = last
                resp.log_term = int(v.log_term[lane, last & (self.shape.w - 1)])
            if rd.snapshot:
                resp.snapshot = rd.snapshot
            m.responses.append(resp)
        return m

    def _storage_apply_msg(self, lane: int, rd: Ready) -> Message:
        """reference: rawnode.go:374-399 newStorageApplyMsg."""
        nid = self.id_of(lane)
        ents = list(rd.committed_entries)
        return Message(
            type=int(MT.MSG_STORAGE_APPLY),
            to=LOCAL_APPLY_THREAD,
            frm=nid,
            entries=ents,
            responses=[
                Message(
                    type=int(MT.MSG_STORAGE_APPLY_RESP),
                    to=nid,
                    frm=LOCAL_APPLY_THREAD,
                    entries=ents,
                )
            ],
        )

    def set_async_storage_writes(self, lane: int, on: bool = True):
        """reference: raft.go:160-185 Config.AsyncStorageWrites."""
        self._async[lane] = on
        self._host_dirty()  # the Ready shape (and thus readiness) changed

    def advance(self, lane: int):
        """reference: rawnode.go:479-491 — ack storage, then deliver the
        after-append self-messages."""
        if self._async[lane]:
            raise RuntimeError(
                "Advance must not be called when using AsyncStorageWrites"
            )
        rd = getattr(self, "_accepted", {}).pop(lane, None)
        if rd is None:
            return
        v = self.view
        nid = self.id_of(lane)
        if rd.snapshot and rd.snapshot.index:
            self._run_step(
                lane,
                Message(
                    type=int(MT.MSG_STORAGE_APPEND_RESP),
                    to=nid,
                    snapshot=rd.snapshot,
                ),
            )
        if rd.entries:
            last = rd.entries[-1]
            self._run_step(
                lane,
                Message(
                    type=int(MT.MSG_STORAGE_APPEND_RESP),
                    to=nid,
                    index=last.index,
                    log_term=last.term,
                ),
            )
        if rd.committed_entries:
            last = rd.committed_entries[-1]
            nbytes = sum(len(e.data) for e in rd.committed_entries)
            self._run_step(
                lane,
                Message(
                    type=int(MT.MSG_STORAGE_APPLY_RESP),
                    to=nid,
                    index=last.index,
                    commit=nbytes,
                ),
            )
        pending = self._steps_on_advance[lane]
        self._steps_on_advance[lane] = []
        for m in pending:
            self._run_step(lane, m)
        self._maybe_auto_leave(lane)

    def _maybe_auto_leave(self, lane: int):
        """Leader proposes the empty V2 leave once the joint entry is applied
        (reference: raft.go:717-745 appliedTo)."""
        v = self.view
        if (
            bool(v.auto_leave[lane])
            and int(v.applied[lane]) >= int(v.pending_conf_index[lane])
            and int(v.state[lane]) == int(StateType.LEADER)
            and int(v.lead_transferee[lane]) == 0
        ):
            from raft_tpu import confchange as _ccm

            if self.trace is not None:
                self.trace.auto_leave_initiated(lane)
            try:
                self.propose_conf_change(
                    lane, _ccm.encode(_ccm.ConfChangeV2()), v2=True
                )
            except ErrProposalDropped:
                # retried on a later applied-advance (reference:
                # raft.go:735-743 logs and moves on)
                pass

    # -- restart/recovery (reference: node.go:281-289 RestartNode,
    # raft.go:432-477 newRaft from Storage, doc.go:46-67) ------------------

    def bootstrap_lane(self, lane: int, peers, contexts: dict | None = None):
        """The reference's `StartNode(c, peers)` bootstrap (reference:
        bootstrap.go:30-80 via node.go:271-279): on an EMPTY lane, become
        follower at term 1, synthesize one committed `ConfChangeAddNode`
        entry per peer at indexes 1..k (term 1), and install the membership
        so `campaign()` works immediately. The entries stay UNSTABLE and
        `applied` stays 0, so the application observes every conf change in
        the first Ready (its Entries, HardState{Term:1, Commit:k} and
        CommittedEntries) and re-applies them through `apply_conf_change` —
        the reference's deliberate double-add (bootstrap.go:63-71).

        peers: iterable of raft ids; contexts: optional {id: bytes} riding
        each ConfChange's Context (bootstrap.go:53)."""
        from raft_tpu import confchange as ccm
        from raft_tpu.state import draw_timeout

        peers = list(peers)
        if not peers:
            raise ValueError("must provide at least one peer to bootstrap")
        v = self.view
        if int(v.last[lane]) or int(v.term[lane]) or int(v.snap_index[lane]):
            raise ValueError("can't bootstrap a nonempty lane")
        k = len(peers)
        w = self.shape.w
        if k > w - 1 or k > self.shape.v:
            raise ValueError("too many bootstrap peers for the static shape")

        # the synthesized entries (term 1, indexes 1..k), payloads host-side
        log_term = np.zeros((w,), np.int32)
        log_type = np.zeros((w,), np.int32)
        log_bytes = np.zeros((w,), np.int32)
        for i, pid in enumerate(peers):
            cc = ccm.ConfChange(
                type=int(ccm.ConfChangeType.ADD_NODE),
                node_id=pid,
                context=(contexts or {}).get(pid, b""),
            )
            data = ccm.encode(cc)
            idx = i + 1
            log_term[idx & (w - 1)] = 1
            log_type[idx & (w - 1)] = int(EntryType.ENTRY_CONF_CHANGE)
            log_bytes[idx & (w - 1)] = len(data)
            self.store.put(
                lane, Entry(1, idx, int(EntryType.ENTRY_CONF_CHANGE), data)
            )

        st = self.state
        new_to = draw_timeout(
            st.rng[lane][None], st.cfg.election_tick[lane][None]
        )[0]
        st = dataclasses.replace(
            st,
            # becomeFollower(1, None) (bootstrap.go:50)
            term=st.term.at[lane].set(1),
            vote=st.vote.at[lane].set(0),
            lead=st.lead.at[lane].set(0),
            state=st.state.at[lane].set(int(StateType.FOLLOWER)),
            randomized_election_timeout=(
                st.randomized_election_timeout.at[lane].set(new_to)
            ),
            log_term=st.log_term.at[lane].set(jnp.asarray(log_term)),
            log_type=st.log_type.at[lane].set(
                jnp.asarray(log_type).astype(st.log_type.dtype)
            ),
            log_bytes=st.log_bytes.at[lane].set(jnp.asarray(log_bytes)),
            last=st.last.at[lane].set(k),
            # unstable AND committed (bootstrap.go:73-75) — the first Ready
            # both persists and applies them
            stabled=st.stabled.at[lane].set(0),
            committed=st.committed.at[lane].set(k),
            applying=st.applying.at[lane].set(0),
            applied=st.applied.at[lane].set(0),
        )
        self.state = st
        self.view.refresh(st)

        # applyConfChange per peer (bootstrap.go:76-78): progress.next lands
        # after the bootstrap entries
        cfg = ccm.TrackerConfig(voters_in=set(peers))
        trk = {
            pid: ccm.Progress(match=0, next=k + 1, is_learner=False)
            for pid in peers
        }
        self._write_tracker(lane, cfg, trk)
        # empty prevHardSt so the first Ready emits the bootstrap HardState
        # (bootstrap.go:43-46)
        self._prev_hs[lane] = HardState()
        self._prev_ss[lane] = SoftState(0, int(StateType.FOLLOWER))

    def restart_lane(self, lane: int, storage, applied: int = 0):
        """Rebuild this lane from persisted state — the batched analog of
        `RestartNode`/`NewRawNode` reading `Storage.InitialState` + stored
        entries (reference: node.go:281-289, raft.go:432-477, doc.go:46-67).

        `storage` is a `raft_tpu.storage.MemoryStorage` (or anything with
        its read interface) recovered from disk; `applied` is the caller's
        last applied index (Config.Applied, raft.go:181-186) — entries at or
        below it are not re-emitted in CommittedEntries.
        """
        from raft_tpu import confchange as ccm
        from raft_tpu.state import draw_timeout

        hs, snap_meta = storage.initial_state()
        snap_index = storage.first_index() - 1
        snap_term = storage.term(snap_index) if snap_index else 0
        last = storage.last_index()
        w = self.shape.w
        if last - snap_index > w - 1:
            raise ValueError(
                f"persisted log spans {last - snap_index} entries; device "
                f"window holds {w - 1} — compact the storage before restart"
            )
        if hs.commit > last:
            raise ValueError(
                f"hardstate commit {hs.commit} out of range [0, {last}]"
            )  # reference: raft.go:1972-1976 loadState panic
        # the log's commit floor is the snapshot point even when the
        # HardState is empty (reference: log.go:74-90 newLog starts
        # committed at firstIndex-1; loadState only ever raises it)
        hs = dataclasses.replace(hs, commit=max(hs.commit, snap_index))
        applied = max(applied, snap_index)
        if applied > hs.commit:
            raise ValueError(
                f"applied {applied} cannot exceed committed {hs.commit}"
            )

        nid = self.id_of(lane)
        n, v = self.shape.n, self.shape.v
        # log window columns from storage
        log_term = np.zeros((w,), np.int32)
        log_type = np.zeros((w,), np.int32)
        log_bytes = np.zeros((w,), np.int32)
        self.store.truncate_from(lane, 0)
        for e in storage.entries(snap_index + 1, last + 1) if last > snap_index else []:
            s = e.index & (w - 1)
            log_term[s] = e.term
            log_type[s] = e.type
            log_bytes[s] = len(e.data or b"")
            self.store.put(lane, e)

        st = self.state
        zero_v = jnp.zeros((v,), I32)
        false_v = jnp.zeros((v,), jnp.bool_)
        f = st.infl_index.shape[-1]
        r = st.ro_ctx.shape[-1]
        new_to = draw_timeout(
            st.rng[lane][None], st.cfg.election_tick[lane][None]
        )[0]
        st = dataclasses.replace(
            st,
            # loadState + becomeFollower(term, None) (raft.go:470-476)
            term=st.term.at[lane].set(hs.term),
            vote=st.vote.at[lane].set(hs.vote),
            state=st.state.at[lane].set(int(StateType.FOLLOWER)),
            lead=st.lead.at[lane].set(0),
            lead_transferee=st.lead_transferee.at[lane].set(0),
            pending_conf_index=st.pending_conf_index.at[lane].set(0),
            uncommitted_size=st.uncommitted_size.at[lane].set(0),
            election_elapsed=st.election_elapsed.at[lane].set(0),
            heartbeat_elapsed=st.heartbeat_elapsed.at[lane].set(0),
            randomized_election_timeout=(
                st.randomized_election_timeout.at[lane].set(new_to)
            ),
            log_term=st.log_term.at[lane].set(jnp.asarray(log_term)),
            log_type=st.log_type.at[lane].set(jnp.asarray(log_type)),
            log_bytes=st.log_bytes.at[lane].set(jnp.asarray(log_bytes)),
            last=st.last.at[lane].set(last),
            stabled=st.stabled.at[lane].set(last),
            committed=st.committed.at[lane].set(hs.commit),
            applying=st.applying.at[lane].set(applied),
            applied=st.applied.at[lane].set(applied),
            snap_index=st.snap_index.at[lane].set(snap_index),
            snap_term=st.snap_term.at[lane].set(snap_term),
            pending_snap_index=st.pending_snap_index.at[lane].set(0),
            pending_snap_term=st.pending_snap_term.at[lane].set(0),
            avail_snap_index=st.avail_snap_index.at[lane].set(0),
            avail_snap_term=st.avail_snap_term.at[lane].set(0),
            # empty config until restored below (raft.go:452-461)
            prs_id=st.prs_id.at[lane].set(zero_v),
            voters_in=st.voters_in.at[lane].set(false_v),
            voters_out=st.voters_out.at[lane].set(false_v),
            learners=st.learners.at[lane].set(false_v),
            learners_next=st.learners_next.at[lane].set(false_v),
            auto_leave=st.auto_leave.at[lane].set(False),
            is_learner=st.is_learner.at[lane].set(False),
            pr_match=st.pr_match.at[lane].set(zero_v),
            pr_next=st.pr_next.at[lane].set(jnp.ones((v,), I32)),
            pr_state=st.pr_state.at[lane].set(zero_v),
            pr_pending_snapshot=st.pr_pending_snapshot.at[lane].set(zero_v),
            pr_recent_active=st.pr_recent_active.at[lane].set(false_v),
            pr_msg_app_flow_paused=st.pr_msg_app_flow_paused.at[lane].set(false_v),
            votes=st.votes.at[lane].set(zero_v),
            infl_index=st.infl_index.at[lane].set(jnp.zeros((v, f), I32)),
            infl_bytes=st.infl_bytes.at[lane].set(jnp.zeros((v, f), I32)),
            infl_start=st.infl_start.at[lane].set(zero_v),
            infl_count=st.infl_count.at[lane].set(zero_v),
            infl_total_bytes=st.infl_total_bytes.at[lane].set(zero_v),
            ro_ctx=st.ro_ctx.at[lane].set(jnp.zeros((r,), I32)),
            ro_from=st.ro_from.at[lane].set(jnp.zeros((r,), I32)),
            ro_index=st.ro_index.at[lane].set(jnp.zeros((r,), I32)),
            ro_acks=st.ro_acks.at[lane].set(jnp.zeros((r, v), jnp.bool_)),
            ro_seq=st.ro_seq.at[lane].set(jnp.zeros((r,), I32)),
            ro_next_seq=st.ro_next_seq.at[lane].set(1),
            pri_ctx=st.pri_ctx.at[lane].set(jnp.zeros((r,), I32)),
            pri_from=st.pri_from.at[lane].set(jnp.zeros((r,), I32)),
            rs_ctx=st.rs_ctx.at[lane].set(jnp.zeros((r,), I32)),
            rs_index=st.rs_index.at[lane].set(jnp.zeros((r,), I32)),
            rs_count=st.rs_count.at[lane].set(0),
            error_bits=st.error_bits.at[lane].set(0),
        )
        self.state = st
        self.view.refresh(st)

        # membership from the snapshot's ConfState via confchange.Restore
        # (raft.go:452-461); empty ConfState = membership rebuilt by the app
        # re-applying committed conf-change entries above `applied`
        cs = ccm.ConfState(
            voters=tuple(snap_meta.voters),
            learners=tuple(snap_meta.learners),
            voters_outgoing=tuple(snap_meta.voters_outgoing),
            learners_next=tuple(snap_meta.learners_next),
            auto_leave=bool(snap_meta.auto_leave),
        )
        if cs.voters or cs.learners or cs.voters_outgoing:
            cfg, trk = ccm.restore(cs, last_index=last)
            if nid in trk:
                # the local node's progress is fully caught up with itself
                # (confchange/restore.go:144-155 via Changer.initProgress)
                trk[nid].match = last
                trk[nid].next = last + 1
            self._write_tracker(lane, cfg, trk)
        if snap_meta.index:
            self.set_app_snapshot(lane, snap_meta)

        # host bookkeeping resets (fresh RawNode over recovered state;
        # rawnode.go:51-66 seeds prev hard/soft state so the boot state
        # does not surface as a spurious first Ready)
        self._msgs[lane] = []
        self._after_append[lane] = []
        self._steps_on_advance[lane] = []
        self._read_states[lane] = []
        self._inprog[lane] = 0
        self._snap_inprog[lane] = 0
        self._applying[lane] = applied
        self._prev_hs[lane] = HardState(hs.term, hs.vote, hs.commit)
        self._prev_ss[lane] = SoftState(0, int(StateType.FOLLOWER))
        getattr(self, "_accepted", {}).pop(lane, None)

    # -- snapshot/compaction (reference: storage.go:227-272) ---------------

    def set_app_snapshot(self, lane: int, snap: Snapshot):
        """Install the application's latest snapshot — the one
        Storage.Snapshot() returns and leaders ship in MsgSnap (reference:
        storage.go:79-84, raft.go:636-649)."""
        self.store.set_snapshot(lane, snap)
        st = self.state
        self.state = dataclasses.replace(
            st,
            avail_snap_index=st.avail_snap_index.at[lane].set(snap.index),
            avail_snap_term=st.avail_snap_term.at[lane].set(snap.term),
        )
        self.view.refresh(self.state)

    def rebase_group(self, lanes, delta: int | None = None) -> int:
        """Index re-keying after snapshot+compact — the recovery path for
        the i32 device index space (reference indexes are uint64,
        raftpb/raft.proto:21-26; ops/log.py flags ERR_INDEX_NEAR_OVERFLOW
        at 2^30). Shifts every index down by `delta` (default: the largest
        window-aligned value below the group's min snap_index) on the given
        lanes — pass ALL members of a group homed here so in-flight message
        indexes stay consistent. Host mirrors (payload store keys, HardState
        history, async cursors) shift too. Requires the lanes' host queues
        to be drained (call between a full Ready/advance cycle). Returns the
        delta applied; Ready output after this is the reference's, shifted
        down by exactly the accumulated rebase offset."""
        lanes = list(lanes)
        w = self.shape.w
        v = self.view
        if delta is None:
            delta = (min(int(v.snap_index[l]) for l in lanes) // w) * w
        if delta <= 0:
            return 0
        if delta & (w - 1):
            raise ValueError("rebase delta must be a multiple of the window")
        for lane in lanes:
            if (
                self._msgs[lane]
                or self._after_append[lane]
                or self._steps_on_advance[lane]
                or self._read_states[lane]
            ):
                raise RuntimeError(
                    f"lane {lane} has queued messages; rebase requires a "
                    "drained Ready/advance cycle"
                )
        from raft_tpu.ops import log as lg

        # collect live window payloads before the shift (store-agnostic:
        # works for both the Python dict store and the C++ arena)
        kept: dict[int, list] = {}
        for lane in lanes:
            rows = []
            lt = v.log_term[lane]
            lty = v.log_type[lane]
            for i in range(int(v.snap_index[lane]) + 1, int(v.last[lane]) + 1):
                term = int(lt[i & (w - 1)])
                etype, data = self.store.get(lane, i, term)
                rows.append((i, term, int(lty[i & (w - 1)]), data))
            kept[lane] = rows

        mask = jnp.zeros((self.shape.n,), bool)
        dl = jnp.zeros((self.shape.n,), I32)
        for lane in lanes:
            mask = mask.at[lane].set(True)
            dl = dl.at[lane].set(delta)
        # the shared module-level jit (ops/fused.py): a fresh jax.jit
        # wrapper here would retrace/recompile on every rebase call. The
        # copying variant on purpose — _StateView may hold zero-copy host
        # views of the input state.
        from raft_tpu.ops.fused import _rebase_indexes_jit

        self.state = _rebase_indexes_jit(self.state, mask, dl)
        self.view.refresh(self.state)
        for lane in lanes:
            # payload store re-key: clear, re-put shifted
            self.store.compact_below(lane, (1 << 31) - 1)
            for i, term, etype, data in kept[lane]:
                self.store.put(lane, Entry(term, i - delta, etype, data))
            snap = self.store.snapshot(lane)
            if snap is not None:
                snap.index -= delta
            hs = self._prev_hs[lane]
            self._prev_hs[lane] = HardState(
                hs.term, hs.vote, max(hs.commit - delta, 0)
            )
            self._inprog[lane] = max(self._inprog[lane] - delta, 0)
            self._snap_inprog[lane] = max(self._snap_inprog[lane] - delta, 0)
            self._applying[lane] = max(self._applying[lane] - delta, 0)
        return delta

    def set_snapshot_unavailable(self, lane: int, on: bool = True):
        """Storage.Snapshot() deferral (reference: storage.go:36-38
        ErrSnapshotTemporarilyUnavailable): while on, the leader's MsgSnap
        fallback is skipped without error and retried after clearing —
        raft.go:625-649's non-panicking skip path."""
        st = self.state
        self.state = dataclasses.replace(
            st, snap_unavailable=st.snap_unavailable.at[lane].set(on)
        )
        self.view.refresh(self.state)

    def compact(self, lane: int, to_index: int, data: bytes = b""):
        """App-driven compaction: CreateSnapshot(to_index, data) + Compact
        (reference: storage.go:227-272). to_index must be <= applied."""
        v = self.view
        if to_index > int(v.applied[lane]):
            raise ValueError("cannot compact beyond applied")
        if to_index <= int(v.snap_index[lane]):
            return
        w = self.shape.w
        term = int(v.log_term[lane, to_index & (w - 1)])
        from raft_tpu.ops import log as lg

        mask_idx = jnp.zeros((self.shape.n,), I32).at[lane].set(to_index)
        mask_term = jnp.zeros((self.shape.n,), I32).at[lane].set(term)
        self.state = lg.compact(self.state, mask_idx, mask_term)
        self.view.refresh(self.state)
        self.store.compact_below(lane, to_index + 1)
        self.set_app_snapshot(
            lane,
            Snapshot(
                index=to_index,
                term=term,
                data=data,
                voters=self.peer_ids(lane, voters=True),
                learners=self.peer_ids(lane, learners=True),
            ),
        )

    # -- conf changes (reference: raft.go:1888-1970, node.go ApplyConfChange)

    def _extract_tracker(self, lane: int):
        from raft_tpu import confchange as ccm

        v = self.view
        cfg = ccm.TrackerConfig(auto_leave=bool(v.auto_leave[lane]))
        trk: dict[int, ccm.Progress] = {}
        for j in range(self.shape.v):
            nid = int(v.prs_id[lane, j])
            if not nid:
                continue
            if v.voters_in[lane, j]:
                cfg.voters_in.add(nid)
            if v.voters_out[lane, j]:
                cfg.voters_out.add(nid)
            if v.learners[lane, j]:
                cfg.learners.add(nid)
            if v.learners_next[lane, j]:
                cfg.learners_next.add(nid)
            trk[nid] = ccm.Progress(
                match=int(v.pr_match[lane, j]),
                next=int(v.pr_next[lane, j]),
                state=int(v.pr_state[lane, j]),
                is_learner=bool(v.learners[lane, j]),
                recent_active=bool(v.pr_recent_active[lane, j]),
                msg_app_flow_paused=bool(v.pr_msg_app_flow_paused[lane, j]),
                pending_snapshot=int(v.pr_pending_snapshot[lane, j]),
            )
        return cfg, trk

    def _write_tracker(self, lane: int, cfg, trk):
        """Install (cfg, trk) into the lane's membership/progress rows.
        Surviving ids keep their slots (so untouched progress — including
        inflight windows — carries over); removed slots are cleared; new ids
        land in free slots."""
        v = self.shape.v
        view = self.view
        cur = [int(view.prs_id[lane, j]) for j in range(v)]
        ids = set(trk)
        if len(ids) > v:
            raise ValueError(f"config needs {len(ids)} slots, capacity {v}")
        slot_of: dict[int, int] = {}
        for j, nid in enumerate(cur):
            if nid and nid in ids:
                slot_of[nid] = j
        free = [j for j in range(v) if cur[j] not in ids or not cur[j]]
        for nid in sorted(ids - set(slot_of)):
            slot_of[nid] = free.pop(0)

        import numpy as np_

        prs_id = np_.zeros((v,), np_.int32)
        m_in = np_.zeros((v,), bool)
        m_out = np_.zeros((v,), bool)
        m_l = np_.zeros((v,), bool)
        m_ln = np_.zeros((v,), bool)
        pr_match = np_.zeros((v,), np_.int32)
        pr_next = np_.ones((v,), np_.int32)
        pr_state = np_.zeros((v,), np_.int32)
        pr_ra = np_.zeros((v,), bool)
        pr_paused = np_.zeros((v,), bool)
        pr_psnap = np_.zeros((v,), np_.int32)
        for nid, j in slot_of.items():
            pr = trk[nid]
            prs_id[j] = nid
            m_in[j] = nid in cfg.voters_in
            m_out[j] = nid in cfg.voters_out
            m_l[j] = nid in cfg.learners
            m_ln[j] = nid in cfg.learners_next
            pr_match[j] = pr.match
            pr_next[j] = pr.next
            pr_state[j] = pr.state
            pr_ra[j] = pr.recent_active
            pr_paused[j] = pr.msg_app_flow_paused
            pr_psnap[j] = pr.pending_snapshot

        nid_self = self.id_of(lane)
        st = self.state
        st = dataclasses.replace(
            st,
            prs_id=st.prs_id.at[lane].set(prs_id),
            voters_in=st.voters_in.at[lane].set(m_in),
            voters_out=st.voters_out.at[lane].set(m_out),
            learners=st.learners.at[lane].set(m_l),
            learners_next=st.learners_next.at[lane].set(m_ln),
            auto_leave=st.auto_leave.at[lane].set(cfg.auto_leave),
            pr_match=st.pr_match.at[lane].set(pr_match),
            pr_next=st.pr_next.at[lane].set(pr_next),
            pr_state=st.pr_state.at[lane].set(pr_state),
            pr_recent_active=st.pr_recent_active.at[lane].set(pr_ra),
            pr_msg_app_flow_paused=st.pr_msg_app_flow_paused.at[lane].set(pr_paused),
            pr_pending_snapshot=st.pr_pending_snapshot.at[lane].set(pr_psnap),
            is_learner=st.is_learner.at[lane].set(nid_self in cfg.learners),
        )
        self.state = st
        self.view.refresh(st)

    def apply_conf_change(self, lane: int, cc) -> "object":
        """Apply a committed conf change; returns the resulting ConfState
        (reference: raft.go:1888-1970 applyConfChange/switchToConfig)."""
        from raft_tpu import confchange as ccm

        cc2 = cc.as_v2()
        cfg0, trk0 = self._extract_tracker(lane)
        last = int(self.view.last[lane])
        ch = ccm.Changer(cfg0, trk0, last)
        if cc2.leave_joint():
            cfg, trk = ch.leave_joint()
        else:
            auto_leave, use_joint = cc2.enter_joint()
            if use_joint:
                cfg, trk = ch.enter_joint(auto_leave, cc2.changes)
            else:
                cfg, trk = ch.simple(cc2.changes)
        self._write_tracker(lane, cfg, trk)

        nid = self.id_of(lane)
        removed_or_learner = nid not in cfg.voters_in | cfg.voters_out
        step_down = bool(
            np.asarray(self.state.cfg.step_down_on_removal[lane])
        ) and (removed_or_learner or nid in cfg.learners)
        st = self.state
        if step_down and int(self.view.state[lane]) == int(StateType.LEADER):
            # becomeFollower(term, None) at unchanged term (raft.go:1930-1936)
            st = dataclasses.replace(
                st,
                state=st.state.at[lane].set(int(StateType.FOLLOWER)),
                lead=st.lead.at[lane].set(0),
                lead_transferee=st.lead_transferee.at[lane].set(0),
                election_elapsed=st.election_elapsed.at[lane].set(0),
            )
            self.state = st
            self.view.refresh(st)
        # leader follow-ups on device (commit under new quorum / probe newcomers)
        mask = jnp.zeros((self.shape.n,), bool).at[lane].set(True)
        self.state, out = self._post_cc_fn(self.state, mask)
        self.view.refresh(self.state)
        self._collect_out(out)
        return ccm.conf_state(cfg)

    # -- introspection -----------------------------------------------------

    def id_of(self, lane: int) -> int:
        return int(self.view.id[lane])

    def peer_ids(self, lane: int, voters=False, learners=False) -> tuple:
        v = self.view
        ids = v.prs_id[lane]
        if voters:
            m = v.voters_in[lane]
        elif learners:
            m = v.learners[lane]
        else:
            m = ids != 0
        return tuple(int(x) for x in np.sort(ids[m & (ids != 0)]))

    def basic_status(self, lane: int) -> dict:
        """reference: status.go:26-42."""
        v = self.view
        return {
            "id": self.id_of(lane),
            "term": int(v.term[lane]),
            "vote": int(v.vote[lane]),
            "commit": int(v.committed[lane]),
            "lead": int(v.lead[lane]),
            "raft_state": StateType(int(v.state[lane])).name,
            "applied": int(v.applied[lane]),
            "lead_transferee": int(v.lead_transferee[lane]),
        }

    def status(self, lane: int) -> dict:
        """reference: status.go:44-76 — adds config + progress when leader."""
        st = self.basic_status(lane)
        v = self.view
        st["config"] = {
            "voters": self.peer_ids(lane, voters=True),
            "voters_outgoing": tuple(
                int(x)
                for x in np.sort(v.prs_id[lane][v.voters_out[lane]])
                if x
            ),
            "learners": self.peer_ids(lane, learners=True),
            "auto_leave": bool(v.auto_leave[lane]),
        }
        if int(v.state[lane]) == int(StateType.LEADER):
            st["progress"] = {
                pid: self._progress_row(lane, j)
                for pid, j in self._peer_slots(lane)
            }
        return st

    def _peer_slots(self, lane: int):
        """Configured (id, slot) pairs in ascending id order (the reference's
        tracker.go:193-213 sorted Visit)."""
        v = self.view
        return sorted(
            (int(v.prs_id[lane, j]), j)
            for j in range(self.shape.v)
            if int(v.prs_id[lane, j])
        )

    def _progress_row(self, lane: int, j: int) -> dict:
        v = self.view
        return {
            "match": int(v.pr_match[lane, j]),
            "next": int(v.pr_next[lane, j]),
            "state": ProgressState(int(v.pr_state[lane, j])).name,
            "paused": bool(v.pr_msg_app_flow_paused[lane, j]),
            "pending_snapshot": int(v.pr_pending_snapshot[lane, j]),
            "recent_active": bool(v.pr_recent_active[lane, j]),
            "is_learner": bool(v.learners[lane, j]),
        }

    _GO_STATE = {
        "FOLLOWER": "StateFollower",
        "CANDIDATE": "StateCandidate",
        "LEADER": "StateLeader",
        "PRE_CANDIDATE": "StatePreCandidate",
    }
    _GO_PR_STATE = {
        "PROBE": "StateProbe",
        "REPLICATE": "StateReplicate",
        "SNAPSHOT": "StateSnapshot",
    }

    def status_json(self, lane: int) -> str:
        """The reference's Status.MarshalJSON wire format, byte-for-byte
        (reference: status.go:78-97): ids in lowercase hex, states as Go
        strings, progress sub-objects with match/next/state only."""
        st = self.status(lane)
        j = (
            '{"id":"%x","term":%d,"vote":"%x","commit":%d,"lead":"%x",'
            '"raftState":"%s","applied":%d,"progress":{'
            % (
                st["id"], st["term"], st["vote"], st["commit"], st["lead"],
                self._GO_STATE[st["raft_state"]], st["applied"],
            )
        )
        parts = [
            '"%x":{"match":%d,"next":%d,"state":"%s"}'
            % (pid, p["match"], p["next"], self._GO_PR_STATE[p["state"]])
            for pid, p in sorted(st.get("progress", {}).items())
        ]
        j += ",".join(parts) + '},"leadtransferee":"%x"}' % st["lead_transferee"]
        return j

    def with_progress(self, lane: int, visitor):
        """Progress iteration in ascending id order (reference:
        rawnode.go:516-528 WithProgress, tracker.go:193-213 Visit).
        visitor(id, typ, progress_dict) with typ one of "ProgressTypePeer" /
        "ProgressTypeLearner"."""
        for pid, j in self._peer_slots(lane):
            pr = self._progress_row(lane, j)
            typ = "ProgressTypeLearner" if pr["is_learner"] else "ProgressTypePeer"
            visitor(pid, typ, pr)


class RawNode:
    """Single-node view onto one lane of a RawNodeBatch — the reference's
    `RawNode` API shape (reference: rawnode.go:34-66)."""

    def __init__(self, batch: RawNodeBatch, lane: int):
        self.batch = batch
        self.lane = lane

    def tick(self):
        self.batch.tick(self.lane)

    def campaign(self):
        self.batch.campaign(self.lane)

    def propose(self, data: bytes):
        self.batch.propose(self.lane, data)

    def step(self, msg: Message):
        self.batch.step(self.lane, msg)

    def has_ready(self) -> bool:
        return self.batch.has_ready(self.lane)

    def ready(self) -> Ready:
        return self.batch.ready(self.lane)

    def advance(self):
        self.batch.advance(self.lane)

    def status(self) -> dict:
        return self.batch.status(self.lane)

    def basic_status(self) -> dict:
        return self.batch.basic_status(self.lane)

    def status_json(self) -> str:
        return self.batch.status_json(self.lane)

    def with_progress(self, visitor):
        self.batch.with_progress(self.lane, visitor)

    def transfer_leadership(self, transferee: int):
        self.batch.transfer_leadership(self.lane, transferee)

    def report_unreachable(self, peer: int):
        self.batch.report_unreachable(self.lane, peer)

    def report_snapshot(self, peer: int, ok: bool):
        self.batch.report_snapshot(self.lane, peer, ok)

    def read_index(self, ctx: int):
        self.batch.read_index(self.lane, ctx)
